"""Tests for the benchmark-problem generators and the generate /
distribute CLI commands (reference: ``pydcop/commands/generators``)."""

import json

from tests.test_cli import run_cli

from pydcop_tpu.dcop.yamldcop import load_dcop


def gen(tmp_path, *args):
    out = tmp_path / "out.yaml"
    r = run_cli("generate", *args, "--output", str(out))
    assert r.returncode == 0, r.stderr
    return load_dcop(out.read_text()), out


def test_graph_coloring_grid(tmp_path):
    dcop, _ = gen(
        tmp_path,
        "graph_coloring", "-n", "9", "-c", "3", "--graph", "grid",
    )
    assert len(dcop.variables) == 9
    # 3x3 grid: 12 edges
    assert len(dcop.constraints) == 12
    assert len(dcop.agents) == 9
    for c in dcop.constraints.values():
        assert c.arity == 2


def test_graph_coloring_soft_noise_roundtrip(tmp_path):
    dcop, out = gen(
        tmp_path,
        "graph_coloring", "-n", "6", "-c", "3", "--soft",
        "--noise", "0.05", "--seed", "7",
    )
    # noisy cost variables survive the yaml round-trip
    again = load_dcop(out.read_text())
    v = next(iter(again.variables.values()))
    assert v.cost_for_val(again.domains["colors"].values[0]) > 0


def test_graph_coloring_deterministic(tmp_path):
    _, out1 = gen(tmp_path, "graph_coloring", "-n", "8", "--seed", "3")
    text1 = out1.read_text()
    _, out2 = gen(tmp_path, "graph_coloring", "-n", "8", "--seed", "3")
    assert out2.read_text() == text1


def test_graph_coloring_scalefree(tmp_path):
    dcop, _ = gen(
        tmp_path,
        "graph_coloring", "-n", "12", "--graph", "scalefree", "-m", "2",
    )
    assert len(dcop.variables) == 12
    assert len(dcop.constraints) >= 12


def test_ising(tmp_path):
    dcop, _ = gen(tmp_path, "ising", "--row_count", "4")
    assert len(dcop.variables) == 16
    # 4x4 torus: 32 couplings + 16 fields
    binary = [c for c in dcop.constraints.values() if c.arity == 2]
    unary = [c for c in dcop.constraints.values() if c.arity == 1]
    assert len(binary) == 32
    assert len(unary) == 16


def test_meeting_scheduling(tmp_path):
    dcop, _ = gen(
        tmp_path,
        "meeting_scheduling", "-s", "4", "-e", "3", "-r", "3",
        "--max_resources_event", "2",
    )
    # PEAV: one variable per (event, resource) attendance
    assert len(dcop.variables) == 6
    assert dcop.dist_hints is not None
    pinned = [
        c for cs in dcop.dist_hints.must_host_map.values() for c in cs
    ]
    assert sorted(pinned) == sorted(dcop.variables)


def test_secp(tmp_path):
    dcop, _ = gen(
        tmp_path, "secp", "-l", "5", "-m", "3", "-r", "2",
    )
    assert len(dcop.variables) == 5
    names = set(dcop.constraints)
    assert sum(n.startswith("eff_") for n in names) == 5
    assert sum(n.startswith("mod") for n in names) == 3
    assert sum(n.startswith("rule") for n in names) == 2


def test_agents_generator(tmp_path):
    out = tmp_path / "agents.yaml"
    r = run_cli(
        "generate", "agents", "-n", "4", "--capacity", "42",
        "--output", str(out),
    )
    assert r.returncode == 0, r.stderr
    import yaml

    data = yaml.safe_load(out.read_text())
    assert len(data["agents"]) == 4
    assert all(a["capacity"] == 42 for a in data["agents"].values())


def test_generate_then_solve(tmp_path):
    _, out = gen(
        tmp_path, "graph_coloring", "-n", "6", "-c", "3", "--soft",
    )
    r = run_cli("solve", str(out), "-a", "dsa", "--rounds", "30")
    assert r.returncode == 0, r.stderr
    result = json.loads(r.stdout)
    assert result["status"] == "finished"


def test_distribute_command(tmp_path):
    _, out = gen(tmp_path, "graph_coloring", "-n", "6", "-c", "3")
    mapping_file = tmp_path / "dist.yaml"
    r = run_cli(
        "distribute", str(out), "-d", "heur_comhost", "-a", "dsa",
        "--output", str(mapping_file),
    )
    assert r.returncode == 0, r.stderr
    result = json.loads(r.stdout)
    assert "cost" in result and "distribution" in result
    import yaml

    mapping = yaml.safe_load(mapping_file.read_text())["distribution"]
    hosted = sorted(c for comps in mapping.values() for c in comps)
    assert hosted == [f"v{i:05d}" for i in range(6)]


def test_task_scheduling(tmp_path):
    import numpy as np

    dcop, _ = gen(
        tmp_path,
        "task_scheduling", "--nb_tasks", "12", "--nb_slots", "6",
        "--window", "4", "--stride", "2", "--seed", "3",
    )
    assert len(dcop.variables) == 12
    assert len(dcop.agents) == 12
    # windows anchor every stride plus the forced tail window
    wins = [n for n in dcop.constraints if n.startswith("win")]
    assert len(wins) == 5
    # the sparse-workload contract: every window table >= 90% +inf
    for n in wins:
        m = np.asarray(
            dcop.constraints[n].as_matrix().matrix, dtype=np.float64
        )
        assert m.shape == (6,) * 4
        assert float(np.isposinf(m).mean()) >= 0.9
    # +inf cells survive the yaml round-trip (the gen() helper
    # already re-loaded from disk — spot-check a table carries inf)
    assert any(
        np.isposinf(
            np.asarray(dcop.constraints[n].as_matrix().matrix)
        ).any()
        for n in wins
    )


def test_task_scheduling_deterministic(tmp_path):
    _, out1 = gen(
        tmp_path, "task_scheduling", "--nb_tasks", "10", "--seed", "5",
    )
    text1 = out1.read_text()
    _, out2 = gen(
        tmp_path, "task_scheduling", "--nb_tasks", "10", "--seed", "5",
    )
    assert out2.read_text() == text1


def test_task_scheduling_planted_schedule_feasible(tmp_path):
    """The planted schedule's pairs are never forbidden, so every
    instance has a zero-lateness optimum — and the sparse format
    solves it bit-identically to dense."""
    import numpy as np

    from pydcop_tpu.api import solve

    dcop, _ = gen(
        tmp_path,
        "task_scheduling", "--nb_tasks", "10", "--nb_slots", "6",
        "--window", "4", "--seed", "7",
    )
    rd = solve(dcop, "dpop", {"util_device": "always"})
    assert np.isfinite(rd["cost"])
    assert rd["cost"] == 0.0  # the planted schedule
    rs = solve(
        dcop, "dpop", {"util_device": "always"},
        table_format="sparse",
    )
    assert rs["assignment"] == rd["assignment"]
    assert rs["cost"] == rd["cost"]


def test_task_scheduling_validation():
    from argparse import Namespace

    import pytest

    from pydcop_tpu.commands.generators.taskscheduling import generate

    def args(**kw):
        base = dict(
            nb_tasks=8, nb_slots=6, window=4, stride=2,
            forbid_density=0.5, lateness_weight=1.0,
            capacity=100.0, seed=0,
        )
        base.update(kw)
        return Namespace(**base)

    with pytest.raises(ValueError, match="window"):
        generate(args(window=1))
    with pytest.raises(ValueError, match="stride"):
        generate(args(stride=0))
    with pytest.raises(ValueError, match="forbid_density"):
        generate(args(forbid_density=1.0))


def test_task_scheduling_sparse_fits_where_dense_cannot():
    """The headline sparse claim: at the same ``max_util_bytes`` and
    lane cap, the dense planner CANNOT hold the workload (every cut
    within the lane budget leaves an oversized table) while the
    sparse planner — sizing hard-capped nodes at their packed
    estimate — plans it."""
    from argparse import Namespace

    import pytest

    from pydcop_tpu.commands.generators.taskscheduling import generate
    from pydcop_tpu.ops.membound import MemboundError, plan_cut
    from pydcop_tpu.ops.semiring import build_plan

    dcop = generate(
        Namespace(
            nb_tasks=16, nb_slots=8, window=5, stride=2,
            forbid_density=0.5, lateness_weight=1.0,
            capacity=100.0, seed=5,
        )
    )
    plan = build_plan(dcop, order="pseudo_tree")
    with pytest.raises(MemboundError):
        plan_cut(plan, 4096, max_cut_lanes=1024)
    cp = plan_cut(
        plan, 4096, max_cut_lanes=1024, table_format="sparse"
    )
    assert cp.table_format == "sparse"
    assert cp.bounded_peak_cells <= cp.budget_cells
