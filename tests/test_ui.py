"""Live observability bridge: one SSE client follows a short solve.

VERDICT r2 item 6 done-criterion: a client driven through a short
solve sees monotone cycles and the final cost; CLI --uiport accepted.
"""

import json
import socket
import threading
import urllib.request

import numpy as np

from pydcop_tpu.api import solve
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import Domain, Variable
from pydcop_tpu.dcop.relations import NAryMatrixRelation


def _ring_dcop(n=12):
    dom = Domain("colors", "", [0, 1, 2])
    dcop = DCOP("ring")
    vs = [Variable(f"v{i}", dom) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    eye = np.eye(3)
    for i in range(n):
        dcop.add_constraint(
            NAryMatrixRelation(
                [vs[i], vs[(i + 1) % n]], eye, name=f"c{i}"
            )
        )
    return dcop


def _free_port():
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def test_sse_client_follows_solve():
    port = _free_port()
    events = []
    ready = threading.Event()

    def client():
        req = urllib.request.urlopen(
            f"http://localhost:{port}/events", timeout=30
        )
        ready.set()
        for raw in req:
            line = raw.decode().strip()
            if line.startswith("data: "):
                events.append(json.loads(line[6:]))

    # start the server first so the client can connect before solving
    from pydcop_tpu.infrastructure.ui import UiServer, chunk_publisher

    ui = UiServer(port)
    t = threading.Thread(target=client, daemon=True)
    t.start()
    ready.wait(10)

    from pydcop_tpu.algorithms import (
        load_algorithm_module,
        prepare_algo_params,
    )
    from pydcop_tpu.engine.batched import run_batched
    from pydcop_tpu.ops import compile_dcop

    problem = compile_dcop(_ring_dcop())
    module = load_algorithm_module("maxsum")
    params = prepare_algo_params({}, module.algo_params)
    result = run_batched(
        problem, module, params, rounds=64, seed=1, chunk_size=8,
        chunk_callback=chunk_publisher(ui),
    )
    ui.publish(
        result.cycles, result.cost, result.best_cost,
        values=result.best_assignment, status=result.status,
    )
    ui.close()
    t.join(10)

    assert len(events) >= 7  # interior chunk boundaries + final
    cycles = [e["cycle"] for e in events]
    assert cycles == sorted(cycles)  # monotone
    final = events[-1]
    assert final["cycle"] == 64
    assert final["cost"] == result.cost
    assert final["values"] == result.best_assignment
    assert final["status"] == "finished"


def test_solve_ui_port_end_to_end():
    port = _free_port()
    collected = []

    # connect shortly after solve() starts serving
    def delayed_client():
        import time

        req = None
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            try:
                req = urllib.request.urlopen(
                    f"http://localhost:{port}/events", timeout=30
                )
                break
            except OSError:
                time.sleep(0.05)
        if req is None:
            return
        try:
            for raw in req:
                line = raw.decode().strip()
                if line.startswith("data: "):
                    collected.append(json.loads(line[6:]))
        except OSError:
            pass

    t = threading.Thread(target=delayed_client, daemon=True)
    t.start()
    # enough chunks that the client connects mid-run even when the
    # chunk runner is already compiled (runner cache warm from other
    # tests makes a short run finish before the client's first poll)
    result = solve(
        _ring_dcop(), "maxsum", rounds=20_000, chunk_size=8, ui_port=port
    )
    t.join(10)
    assert result["cost"] == 0.0
    assert collected, "client saw no events"
    assert collected[-1]["cycle"] == 20_000


def test_state_endpoint():
    from pydcop_tpu.infrastructure.ui import UiServer

    ui = UiServer(0)
    try:
        ui.publish(5, 1.5, 1.0, values={"v0": 1})
        body = urllib.request.urlopen(
            f"http://localhost:{ui.port}/state", timeout=10
        ).read()
        snap = json.loads(body)
        assert snap["cycle"] == 5
        assert snap["values"] == {"v0": 1}
    finally:
        ui.close()
