"""Sharded (shard_map over a mesh) execution tests, on the virtual
8-device CPU mesh — validates the multi-chip path without hardware."""

import jax
import numpy as np
import pytest

from pydcop_tpu.algorithms import load_algorithm_module, prepare_algo_params
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import Domain, Variable
from pydcop_tpu.dcop.relations import NAryMatrixRelation, constraint_from_str
from pydcop_tpu.engine.batched import run_batched
from pydcop_tpu.ops import compile_dcop, encode_assignment, total_cost
from pydcop_tpu.parallel import make_mesh, shard_problem


def coloring_ring(n=24, colors=3, with_ternary=False):
    d = Domain("colors", "", list(range(colors)))
    dcop = DCOP(f"ring{n}")
    vs = [Variable(f"v{i}", d) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    for i in range(n):
        j = (i + 1) % n
        dcop.add_constraint(
            constraint_from_str(f"c{i}", f"1 if v{i} == v{j} else 0", vs)
        )
    if with_ternary:
        for i in range(0, n - 2, 5):
            dcop.add_constraint(
                constraint_from_str(
                    f"t{i}", f"0.5 * (v{i} + v{i+1} + v{i+2})", vs
                )
            )
    return dcop


def test_mesh_has_8_devices():
    assert len(jax.devices()) == 8


def test_shard_major_compile_cost_parity():
    """n_shards layout (ghosts + reorder) must not change any cost."""
    import random

    dcop = coloring_ring(10, 3, with_ternary=True)
    p1 = compile_dcop(dcop, n_shards=1)
    p8 = compile_dcop(dcop, n_shards=8)
    assert p8.n_edges % 8 == 0
    assert p8.n_cons % 8 == 0
    for k, b in p8.buckets.items():
        assert b.tables.shape[0] % 8 == 0
    rnd = random.Random(0)
    for _ in range(10):
        a = {f"v{i}": rnd.randrange(3) for i in range(10)}
        c1 = float(total_cost(p1, encode_assignment(p1, a)))
        c8 = float(total_cost(p8, encode_assignment(p8, a)))
        assert c1 == pytest.approx(c8)


def test_shard_problem_mismatch_raises():
    dcop = coloring_ring(6)
    p = compile_dcop(dcop, n_shards=2)
    mesh = make_mesh(8)
    with pytest.raises(ValueError, match="recompile"):
        shard_problem(p, mesh)


@pytest.mark.parametrize("algo_name", ["dsa", "maxsum", "mgm", "mgm2"])
def test_sharded_matches_unsharded(algo_name):
    """Same compiled problem, same seed: the mesh run must reproduce the
    single-device run (up to float reassociation)."""
    dcop = coloring_ring(24, 3, with_ternary=True)
    problem = compile_dcop(dcop, n_shards=8)
    module = load_algorithm_module(algo_name)
    params = prepare_algo_params({}, module.algo_params)

    r_single = run_batched(problem, module, params, rounds=40, seed=5)
    mesh = make_mesh(8)
    r_mesh = run_batched(
        problem, module, params, rounds=40, seed=5, mesh=mesh
    )
    assert r_mesh.cost == pytest.approx(r_single.cost, abs=1e-4)
    assert r_mesh.best_cost == pytest.approx(r_single.best_cost, abs=1e-4)
    np.testing.assert_allclose(
        r_mesh.cost_trace, r_single.cost_trace, atol=1e-4
    )
    assert r_mesh.assignment == r_single.assignment


def test_sharded_maxsum_solves_tree_exactly():
    d = Domain("d", "", [0, 1, 2])
    dcop = DCOP("tree")
    vs = [Variable(f"v{i}", d) for i in range(9)]
    for v in vs:
        dcop.add_variable(v)
    rng = np.random.RandomState(3)
    for i in range(1, 9):
        m = rng.uniform(0, 10, (3, 3)).round(1)
        dcop.add_constraint(
            NAryMatrixRelation([vs[(i - 1) // 2], vs[i]], m, name=f"c{i}")
        )
    # brute-force optimum via host evaluator
    import itertools

    opt = min(
        dcop.solution_cost(dict(zip([v.name for v in vs], combo)))
        for combo in itertools.product(range(3), repeat=9)
    )
    problem = compile_dcop(dcop, n_shards=8)
    module = load_algorithm_module("maxsum")
    params = prepare_algo_params({"damping": 0.0}, module.algo_params)
    mesh = make_mesh(8)
    r = run_batched(problem, module, params, rounds=30, seed=0, mesh=mesh)
    assert r.best_cost == pytest.approx(opt, rel=1e-5)


def test_ghost_edges_excluded_from_message_count():
    from pydcop_tpu.algorithms import load_algorithm_module

    dcop = coloring_ring(10, 3)  # 10 binary constraints → 20 real edges
    p1 = compile_dcop(dcop, n_shards=1)
    p8 = compile_dcop(dcop, n_shards=8)
    assert p8.n_edges > p1.n_edges  # padding added ghost edges
    module = load_algorithm_module("maxsum")
    assert module.messages_per_round(p1) == 40
    assert module.messages_per_round(p8) == 40  # ghosts not counted


@pytest.mark.parametrize("algo_name", ["dsa", "maxsum"])
def test_restarts_compose_with_mesh(algo_name):
    """n_restarts=4 under an 8-device mesh (vmap inside shard_map):
    the per-restart anytime bests must match the unsharded restart
    run exactly — same RNG streams, per-restart psum exchange."""
    dcop = coloring_ring(24, 3, with_ternary=True)
    module = load_algorithm_module(algo_name)
    params = prepare_algo_params(
        {"variant": "B"} if algo_name == "dsa" else {"damping": 0.5},
        module.algo_params,
    )
    r_flat = run_batched(
        compile_dcop(dcop), module, params, rounds=24, seed=7,
        chunk_size=12, n_restarts=4,
    )
    r_mesh = run_batched(
        compile_dcop(dcop, n_shards=8), module, params, rounds=24,
        seed=7, chunk_size=12, n_restarts=4, mesh=make_mesh(8),
    )
    np.testing.assert_allclose(
        r_mesh.restart_costs, r_flat.restart_costs, atol=1e-4
    )
    assert r_mesh.best_cost == pytest.approx(r_flat.best_cost, abs=1e-4)
    assert r_mesh.assignment == r_flat.assignment


def test_checkpoint_resume_under_mesh(tmp_path):
    """Interrupt a sharded run at its midpoint, resume from the
    checkpoint under the SAME mesh, and land on the uninterrupted
    run's trajectory (the composition claim of engine/batched.py:
    restarts x mesh x checkpoint)."""
    dcop = coloring_ring(24, 3, with_ternary=True)
    problem = compile_dcop(dcop, n_shards=8)
    module = load_algorithm_module("maxsum")
    params = prepare_algo_params({"damping": 0.5}, module.algo_params)
    mesh = make_mesh(8)
    ck = str(tmp_path / "mesh.ckpt.npz")
    full = run_batched(
        problem, module, params, rounds=32, seed=3, mesh=mesh,
        chunk_size=8,
    )
    run_batched(
        problem, module, params, rounds=16, seed=3, mesh=mesh,
        chunk_size=8, checkpoint_path=ck,
    )
    resumed = run_batched(
        problem, module, params, rounds=32, seed=3, mesh=mesh,
        chunk_size=8, checkpoint_path=ck, resume=True,
    )
    assert resumed.best_cost == pytest.approx(full.best_cost, abs=1e-4)
    assert resumed.cost == pytest.approx(full.cost, abs=1e-4)
    assert resumed.assignment == full.assignment


def test_constraint_free_problem_shards():
    """A problem whose surviving variables share NO constraint (every
    neighbor frozen into an external) must still compile and run over
    a mesh — dynamic/elastic reforms hit this shape and used to
    crash-loop on the (1,)-placeholder device_put (round-4 fix:
    ghost-constraint padding covers the empty case; the runner cache
    keys on the problem's tree structure so per-segment recompiles
    cannot reuse a mismatched sharded runner)."""
    from pydcop_tpu.dcop.objects import ExternalVariable

    d = Domain("colors", "", [0, 1, 2])
    dcop = DCOP("frozen_ring")
    vs = []
    for i in range(8):
        v = (
            ExternalVariable(f"v{i}", d, 0)
            if i % 2
            else Variable(f"v{i}", d)
        )
        vs.append(v)
        dcop.add_variable(v)
    for i in range(8):
        dcop.add_constraint(
            constraint_from_str(
                f"c{i}", f"1 if v{i} == v{(i + 1) % 8} else 0", vs
            )
        )
    problem = compile_dcop(dcop, n_shards=8)
    assert problem.n_real_edges == 0  # everything sliced to unary
    module = load_algorithm_module("maxsum")
    params = prepare_algo_params({}, module.algo_params)
    r = run_batched(
        problem, module, params, rounds=4, seed=0, mesh=make_mesh(8),
        chunk_size=4,
    )
    assert r.cycles == 4
    assert module.messages_per_round(problem) == 0  # ghosts not counted
