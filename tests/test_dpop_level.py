"""Level-synchronous DPOP: exactness parity of the batched device
UTIL path against the per-node host f64 oracle, with and without
level-pack padding, single-instance and through ``solve_many``.

The contract under test is BIT-IDENTITY, not approximate equality:
DPOP is exact, the device path is certificate-guarded, and level
batching / pow-2 padding / cross-instance merging only change which
rows ride one dispatch — never a decided value (see
``algorithms/dpop.py`` module docstring).
"""

import numpy as np
import pytest

from pydcop_tpu.api import solve, solve_many
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
from pydcop_tpu.dcop.relations import NAryMatrixRelation
from pydcop_tpu.ops.padding import (
    NO_PADDING,
    as_pad_policy,
    pad_util_parts,
    util_level_key,
)

pytestmark = pytest.mark.dpop

# every joined table goes through the device path (and its
# certificate), however small — the batching logic is what's under
# test, not the auto threshold
DEVICE = {"util_device": "always"}
HOST = {"util_device": "never"}


def random_tree_dcop(n, d, seed, extra_edges=0):
    """Random tree + a few back edges (keeps induced width small but
    exercises pseudo-parents and ragged separator shapes)."""
    rng = np.random.RandomState(seed)
    dom = Domain("dom", "", list(range(d)))
    dcop = DCOP(f"tree{seed}")
    vs = [Variable(f"v{i}", dom) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    for i in range(1, n):
        j = rng.randint(0, i)
        m = rng.uniform(0, 10, (d, d)).round(3)
        dcop.add_constraint(
            NAryMatrixRelation([vs[j], vs[i]], m, name=f"t{j}_{i}")
        )
    for k in range(extra_edges):
        i, j = rng.choice(n, size=2, replace=False)
        m = rng.uniform(0, 5, (d, d)).round(3)
        dcop.add_constraint(
            NAryMatrixRelation(
                [vs[min(i, j)], vs[max(i, j)]], m, name=f"x{k}"
            )
        )
    return dcop


def mixed_arity_dcop(seed):
    """Unary + binary + ternary constraints over mixed domain sizes."""
    rng = np.random.RandomState(seed)
    d2 = Domain("d2", "", [0, 1])
    d3 = Domain("d3", "", [0, 1, 2])
    d4 = Domain("d4", "", [0, 1, 2, 3])
    dcop = DCOP(f"mixed{seed}")
    vs = [
        Variable("a", d3), Variable("b", d2), Variable("c", d4),
        Variable("e", d3), Variable("f", d2), Variable("g", d3),
    ]
    for v in vs:
        dcop.add_variable(v)

    def rel(name, scope):
        shape = tuple(len(v.domain) for v in scope)
        dcop.add_constraint(
            NAryMatrixRelation(
                scope, rng.uniform(0, 8, shape).round(3), name=name
            )
        )

    rel("u0", [vs[0]])
    rel("p0", [vs[0], vs[1]])
    rel("p1", [vs[1], vs[2]])
    rel("p2", [vs[3], vs[4]])
    rel("t0", [vs[0], vs[1], vs[2]])
    rel("t1", [vs[3], vs[4], vs[5]])
    rel("p3", [vs[0], vs[3]])
    return dcop


def assert_identical(r1, r2):
    """Bit-identical solve results: same assignment, same cost."""
    assert r1["assignment"] == r2["assignment"]
    assert r1["cost"] == r2["cost"]
    assert r1["status"] == r2["status"] == "finished"


# -- single instance: device level path vs host f64 oracle -------------


@pytest.mark.parametrize("seed", range(6))
def test_level_batched_matches_host_f64_random_trees(seed):
    dcop = random_tree_dcop(12, 3, seed, extra_edges=2)
    r_host = solve(dcop, "dpop", HOST)
    r_level = solve(dcop, "dpop", DEVICE)
    r_padded = solve(dcop, "dpop", DEVICE, pad_policy="pow2")
    assert_identical(r_level, r_host)
    assert_identical(r_padded, r_host)
    assert r_level["util_backend"] == "device"


@pytest.mark.parametrize("seed", range(4))
def test_per_node_dispatch_matches_level_batched(seed):
    """util_batch='node' (the bench baseline) is the same math as the
    level-synchronous default — only the dispatch granularity
    differs, visible in util_dispatches."""
    dcop = random_tree_dcop(14, 3, seed, extra_edges=1)
    r_node = solve(dcop, "dpop", dict(DEVICE, util_batch="node"))
    r_level = solve(dcop, "dpop", dict(DEVICE, util_batch="level"))
    assert_identical(r_node, r_level)
    assert r_node["util_dispatches"] >= r_level["util_dispatches"]


@pytest.mark.parametrize("seed", range(4))
def test_mixed_arity_parity(seed):
    dcop = mixed_arity_dcop(seed)
    r_host = solve(dcop, "dpop", HOST)
    for params, pad in (
        (DEVICE, "none"),
        (DEVICE, "pow2"),
        (dict(DEVICE, util_batch="node"), "pow2:4"),
    ):
        r = solve(dcop, "dpop", params, pad_policy=pad)
        assert_identical(r, r_host)


def test_tie_heavy_symmetric_falls_back_exact():
    """A fully symmetric problem has margin-0 everywhere: the
    certificate refuses the device result (>10% uncertifiable) and
    each tie-heavy node is redone wholesale on host f64 — per node,
    still exact, counted in dpop.cert_fallbacks, identical under
    padding."""
    from pydcop_tpu.telemetry import session

    dom = Domain("d", "", [0, 1, 2])
    dcop = DCOP("sym")
    vs = [Variable(f"v{i}", dom) for i in range(6)]
    for v in vs:
        dcop.add_variable(v)
    flat = np.ones((3, 3))  # every row constant: margin 0 everywhere
    for i in range(5):
        dcop.add_constraint(
            NAryMatrixRelation([vs[i], vs[i + 1]], flat, name=f"c{i}")
        )
    r_host = solve(dcop, "dpop", HOST)
    with session() as tel:
        r_dev = solve(dcop, "dpop", DEVICE, pad_policy="pow2")
    assert_identical(r_dev, r_host)
    assert r_dev["util_host_nodes"] > 0  # tie-heavy joins fell back
    assert (
        tel.summary()["counters"].get("dpop.cert_fallbacks", 0) >= 1
    )


# -- solve_many: merged level sweep vs K sequential solves -------------


def chain_dcop(n, d, seed):
    """Identical structure across seeds (a path), random tables — the
    canonical one-bucket ``solve_many`` group."""
    rng = np.random.RandomState(seed)
    dom = Domain("dom", "", list(range(d)))
    dcop = DCOP(f"chain{seed}")
    vs = [Variable(f"v{i}", dom) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    for i in range(1, n):
        m = rng.uniform(0, 10, (d, d)).round(3)
        dcop.add_constraint(
            NAryMatrixRelation([vs[i - 1], vs[i]], m, name=f"t{i}")
        )
    return dcop


def test_solve_many_matches_sequential_same_bucket():
    """K same-bucket instances merge into one sweep with bit-identical
    per-instance results; the telemetry counters record the merge."""
    from pydcop_tpu.telemetry import session

    dcops = [chain_dcop(10, 3, 100 + s) for s in range(5)]
    with session() as tel:
        many = solve_many(dcops, "dpop", DEVICE)
    counters = tel.summary()["counters"]
    assert counters.get("dpop.instances_batched") == 5
    assert counters.get("engine.batch_groups") == 1
    assert counters.get("dpop.level_dispatches", 0) >= 1
    for i, d in enumerate(dcops):
        seq = solve(d, "dpop", DEVICE)
        assert many[i]["assignment"] == seq["assignment"]
        assert many[i]["cost"] == seq["cost"]
        assert many[i]["instances_batched"] == 5


def test_solve_many_mixed_buckets_split_groups():
    """Structurally different instances split into separate merged
    groups (problem_group_key), each still exact."""
    dcops = [
        random_tree_dcop(8, 3, 1),
        mixed_arity_dcop(2),
        random_tree_dcop(8, 3, 3),
    ]
    many = solve_many(dcops, "dpop", DEVICE, pad_policy="none")
    for i, d in enumerate(dcops):
        seq = solve(d, "dpop", DEVICE)
        assert many[i]["assignment"] == seq["assignment"]
        assert many[i]["cost"] == seq["cost"]


def test_solve_many_tie_heavy_instance_rides_alone():
    """A tie-heavy instance in a group has its uncertifiable nodes
    redone on host f64 without disturbing the other instances'
    merged device results."""
    dom = Domain("d", "", [0, 1, 2])
    sym = DCOP("sym")
    vs = [Variable(f"v{i}", dom) for i in range(10)]
    for v in vs:
        sym.add_variable(v)
    flat = np.ones((3, 3))  # margin 0 everywhere: certificate refuses
    for i in range(9):
        sym.add_constraint(
            NAryMatrixRelation([vs[i], vs[i + 1]], flat, name=f"c{i}")
        )
    rnd = chain_dcop(10, 3, 7)
    many = solve_many([sym, rnd], "dpop", DEVICE)
    for i, d in enumerate([sym, rnd]):
        seq = solve(d, "dpop", DEVICE)
        assert many[i]["assignment"] == seq["assignment"]
        assert many[i]["cost"] == seq["cost"]
    assert many[0]["util_host_nodes"] > 0  # tie-heavy joins redone
    assert many[1]["util_host_nodes"] == 0  # healthy instance all-device


def test_solve_many_memory_bound_instance_solves_sequentially():
    """memory_bound (MB-DPOP conditioning) instances can't ride the
    merged sweep — they run the sequential path inside the same call,
    exact either way."""
    dcops = [
        random_tree_dcop(9, 3, 11),
        random_tree_dcop(9, 3, 12),
    ]
    many = solve_many(
        dcops, "dpop",
        [dict(DEVICE), dict(DEVICE, memory_bound=27)],
    )
    for i, (d, p) in enumerate(
        zip(dcops, [dict(DEVICE), dict(DEVICE, memory_bound=27)])
    ):
        seq = solve(d, "dpop", p)
        assert many[i]["assignment"] == seq["assignment"]
        assert many[i]["cost"] == seq["cost"]


# -- level-pack keys / padding helpers ---------------------------------


def test_util_level_key_identity_without_padding():
    key = util_level_key((5, 3), ((5, 3), (1, 3)), NO_PADDING)
    assert key == ((5, 3), ((5, 3), (1, 3)))


def test_util_level_key_quantizes_near_miss_shapes():
    pol = as_pad_policy("pow2")
    k1 = util_level_key((5, 5), ((5, 5), (1, 5)), pol)
    k2 = util_level_key((6, 7), ((6, 7), (1, 7)), pol)
    assert k1 == k2  # both land on the (8, 8) lattice point
    # broadcast axes stay 1; the own-axis mask is part of the key
    pshape, pparts = k1
    assert pshape == (8, 8)
    assert pparts == ((8, 8), (1, 8), (1, 8))


def test_pad_util_parts_mask_guards_ghost_cells():
    pol = as_pad_policy("pow2")
    aligned = [
        np.ones((5, 5), dtype=np.float32),
        np.ones((1, 5), dtype=np.float32),
    ]
    pshape, _ = util_level_key((5, 5), [a.shape for a in aligned], pol)
    padded = pad_util_parts(aligned, (5, 5), pshape)
    assert [p.shape for p in padded] == [(8, 8), (1, 8), (1, 8)]
    # real region untouched, ghost cells zero
    assert np.array_equal(padded[0][:5, :5], aligned[0])
    assert np.all(padded[0][5:, :] == 0) and np.all(
        padded[0][:, 5:] == 0
    )
    # mask: exact 0 on real own values, +inf on padded ones
    mask = padded[-1]
    assert np.all(mask[..., :5] == 0.0)
    assert np.all(np.isinf(mask[..., 5:]))


def test_dpop_counters_absent_without_session():
    """No telemetry session ⇒ the counters are a no-op (the hot-path
    contract of the metrics registry)."""
    dcop = random_tree_dcop(8, 3, 42)
    r = solve(dcop, "dpop", DEVICE)  # must not raise
    assert r["status"] == "finished"


def test_dpop_agents_unaffected():
    """Agent declarations ride along untouched (solve ignores them on
    the DPOP path; regression for result-schema drift)."""
    dcop = random_tree_dcop(6, 3, 5)
    dcop.add_agents([AgentDef(f"a{i}") for i in range(6)])
    r = solve(dcop, "dpop", DEVICE, pad_policy="pow2")
    assert set(r["assignment"]) == {f"v{i}" for i in range(6)}
    assert r["util_dispatches"] >= 1
