"""Max-Sum functional tests: exactness on trees, quality on loopy
graphs, parity of the message math against brute force."""

import itertools
import random

import jax.numpy as jnp
import numpy as np
import pytest

from pydcop_tpu.api import solve
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import Domain, Variable, VariableNoisyCostFunc
from pydcop_tpu.dcop.relations import NAryMatrixRelation, constraint_from_str
from pydcop_tpu.utils.expressionfunction import ExpressionFunction


def brute_force_optimum(dcop):
    best, best_cost = None, float("inf")
    names = list(dcop.variables)
    domains = [list(dcop.variables[n].domain.values) for n in names]
    for combo in itertools.product(*domains):
        a = dict(zip(names, combo))
        c = dcop.solution_cost(a)
        if c < best_cost:
            best, best_cost = a, c
    return best, best_cost


def random_tree_dcop(seed, n=7, d=3):
    rnd = random.Random(seed)
    dom = Domain("d", "", list(range(d)))
    dcop = DCOP(f"tree{seed}")
    vs = [Variable(f"v{i}", dom) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    for i in range(1, n):
        parent = rnd.randrange(i)
        m = np.round(
            np.random.RandomState(seed * 50 + i).uniform(0, 10, (d, d)), 1
        )
        dcop.add_constraint(
            NAryMatrixRelation([vs[parent], vs[i]], m, name=f"c{i}")
        )
    return dcop


@pytest.mark.parametrize("seed", [1, 2, 3, 4])
def test_maxsum_exact_on_trees(seed):
    """On acyclic factor graphs Max-Sum is exact."""
    dcop = random_tree_dcop(seed)
    _, opt_cost = brute_force_optimum(dcop)
    result = solve(dcop, "maxsum", {"damping": 0.0, "noise": 0.0}, rounds=30, seed=0)
    assert result["cost"] == pytest.approx(opt_cost, rel=1e-5)


def test_maxsum_ring_coloring():
    dom = Domain("c", "", [0, 1, 2])
    dcop = DCOP("ring")
    n = 10
    vs = []
    for i in range(n):
        # tiny noisy unary costs break the ring's symmetry, as the
        # reference does with VariableNoisyCostFunc
        v = VariableNoisyCostFunc(
            f"v{i}", dom, ExpressionFunction(f"0 * v{i}"), noise_level=0.01
        )
        vs.append(v)
        dcop.add_variable(v)
    for i in range(n):
        j = (i + 1) % n
        dcop.add_constraint(
            constraint_from_str(f"c{i}", f"1 if v{i} == v{j} else 0", vs)
        )
    result = solve(dcop, "maxsum", {"damping": 0.5}, rounds=60, seed=0)
    # proper coloring found (cost < 1 means no violated edge)
    assert result["cost"] < 1.0


def test_maxsum_ternary_constraint():
    dom = Domain("d", "", [0, 1])
    dcop = DCOP("tern")
    vs = [Variable(f"v{i}", dom) for i in range(3)]
    for v in vs:
        dcop.add_variable(v)
    # asymmetric ternary factor with a unique optimum (1, 0, 1)
    dcop.add_constraint(
        constraint_from_str(
            "c", "v0 * 1 + v1 * 7 + (1 - v2) * 3 + (1 - v0) * 2", vs
        )
    )
    dcop.add_constraint(constraint_from_str("u1", "2 * v1", vs))
    _, opt = brute_force_optimum(dcop)
    result = solve(dcop, "maxsum", {"damping": 0.0, "noise": 0.0}, rounds=20, seed=0)
    assert result["cost"] == pytest.approx(opt)
    assert result["assignment"] == {"v0": 1, "v1": 0, "v2": 1}


def test_maxsum_max_mode_tree():
    dom = Domain("d", "", [0, 1, 2])
    dcop = DCOP("maxtree", objective="max")
    vs = [Variable(f"v{i}", dom) for i in range(4)]
    for v in vs:
        dcop.add_variable(v)
    for i in range(1, 4):
        m = np.random.RandomState(i).uniform(0, 10, (3, 3)).round(1)
        dcop.add_constraint(
            NAryMatrixRelation([vs[0], vs[i]], m, name=f"c{i}")
        )
    # brute force max
    best = max(
        sum(
            float(dcop.constraints[f"c{i}"](v0, vi))
            for i, vi in zip(range(1, 4), combo[1:])
        )
        for combo in itertools.product(range(3), repeat=4)
        for v0 in [combo[0]]
    )
    result = solve(dcop, "maxsum", {"damping": 0.0, "noise": 0.0}, rounds=20, seed=0)
    assert result["cost"] == pytest.approx(best)


def test_maxsum_message_count():
    dcop = random_tree_dcop(1, n=5)
    result = solve(dcop, "maxsum", rounds=10, seed=0)
    # 4 binary constraints → 8 directed edges → 16 messages/round
    assert result["msg_count"] == 10 * 16


def test_maxsum_convergence_on_tree():
    dcop = random_tree_dcop(2)
    result = solve(
        dcop, "maxsum", {"damping": 0.0}, rounds=500,
        chunk_size=16, convergence_chunks=2,
    )
    assert result["status"] == "converged"
    assert result["cycle"] < 500


def test_belief_blockdiag_matches_gather():
    """belief='blockdiag' (one static variable-major permutation +
    block-diagonal one-hot MXU matmuls) must reproduce the default
    aggregation: same per-round beliefs up to f32 summation order,
    same best cost on a full run (round-4 layout candidate)."""
    import numpy as np

    import __graft_entry__ as g
    from pydcop_tpu.algorithms import (
        load_algorithm_module,
        prepare_algo_params,
    )
    from pydcop_tpu.algorithms.maxsum import belief_from_r
    from pydcop_tpu.engine.batched import run_batched
    from pydcop_tpu.ops import compile_dcop

    dcop = g._make_coloring_dcop(300, degree=4, seed=6)
    problem = compile_dcop(dcop)
    rng = np.random.RandomState(0)
    r = jnp.asarray(
        rng.rand(problem.d_max, problem.n_edges).astype(np.float32)
    )
    unary_t = jnp.asarray(
        rng.rand(problem.d_max, problem.n_vars).astype(np.float32)
    )
    ref = belief_from_r(problem, r, unary_t, mode="auto")
    blk = belief_from_r(problem, r, unary_t, mode="blockdiag")
    np.testing.assert_allclose(np.asarray(blk), np.asarray(ref), atol=1e-4)

    module = load_algorithm_module("maxsum")
    p_auto = prepare_algo_params({}, module.algo_params)
    p_blk = prepare_algo_params({"belief": "blockdiag"}, module.algo_params)
    r_auto = run_batched(
        problem, module, p_auto, rounds=60, seed=2, chunk_size=30
    )
    r_blk = run_batched(
        problem, module, p_blk, rounds=60, seed=2, chunk_size=30
    )
    assert r_blk.best_cost == pytest.approx(r_auto.best_cost, abs=1e-3)


@pytest.mark.parametrize("seed", [1, 3])
def test_maxsum_bf16_messages_exact_on_trees(seed):
    """msg_dtype='bf16' stores/gathers messages in bfloat16 with f32
    arithmetic: on trees the argmin decisions survive the storage
    rounding and the result stays exact (costs are always exact
    evaluations of the selected assignment)."""
    dcop = random_tree_dcop(seed)
    _, opt_cost = brute_force_optimum(dcop)
    result = solve(
        dcop, "maxsum",
        {"damping": 0.0, "noise": 0.0, "msg_dtype": "bf16"},
        rounds=30, seed=0,
    )
    assert result["cost"] == pytest.approx(opt_cost, rel=1e-5)


def test_maxsum_bf16_messages_ring_coloring():
    """bf16 messages find a proper coloring on the loopy ring too, and
    the sharded mesh path accepts the dtype (f32 psum accumulate)."""
    from pydcop_tpu.algorithms import (
        load_algorithm_module,
        prepare_algo_params,
    )
    from pydcop_tpu.engine.batched import run_batched
    from pydcop_tpu.ops import compile_dcop
    from pydcop_tpu.parallel import make_mesh

    dom = Domain("c", "", [0, 1, 2])
    dcop = DCOP("ring")
    n = 10
    vs = []
    for i in range(n):
        v = VariableNoisyCostFunc(
            f"v{i}", dom, ExpressionFunction(f"0 * v{i}"), noise_level=0.01
        )
        vs.append(v)
        dcop.add_variable(v)
    for i in range(n):
        j = (i + 1) % n
        dcop.add_constraint(
            constraint_from_str(f"c{i}", f"1 if v{i} == v{j} else 0", vs)
        )
    result = solve(
        dcop, "maxsum", {"damping": 0.5, "msg_dtype": "bf16"},
        rounds=60, seed=0,
    )
    assert result["cost"] < 1.0

    module = load_algorithm_module("maxsum")
    params = prepare_algo_params(
        {"damping": 0.5, "msg_dtype": "bf16"}, module.algo_params
    )
    r_mesh = run_batched(
        compile_dcop(dcop, n_shards=8), module, params, rounds=60,
        seed=0, mesh=make_mesh(8),
    )
    assert r_mesh.best_cost < 1.0
