"""Tests for the generic graph helpers (utils/graphs.py)."""

import pytest

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import Domain, Variable
from pydcop_tpu.dcop.relations import constraint_from_str
from pydcop_tpu.utils.graphs import (
    as_bipartite_networkx_graph,
    as_networkx_graph,
    connected_components,
    cycles_count,
    graph_diameter,
    has_cycle,
)

D = Domain("d", "", [0, 1, 2])


def _dcop(edges, n):
    dcop = DCOP("g")
    vs = [Variable(f"v{i}", D) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    for k, (i, j) in enumerate(edges):
        dcop.add_constraint(
            constraint_from_str(f"c{k}", f"1 if v{i} == v{j} else 0", vs)
        )
    return dcop


def test_has_cycle():
    assert not has_cycle(_dcop([(0, 1), (1, 2), (2, 3)], 4))  # path
    assert has_cycle(_dcop([(0, 1), (1, 2), (2, 0)], 3))  # triangle
    assert not has_cycle({})  # empty
    assert has_cycle({0: [1], 1: [2], 2: [0]})  # adjacency input


def test_cycles_count():
    assert cycles_count(_dcop([(0, 1), (1, 2), (2, 3)], 4)) == 0
    assert cycles_count(_dcop([(0, 1), (1, 2), (2, 0)], 3)) == 1
    # two independent cycles sharing an edge chain
    assert (
        cycles_count(
            _dcop([(0, 1), (1, 2), (2, 0), (2, 3), (3, 4), (4, 2)], 5)
        )
        == 2
    )


def test_graph_diameter():
    assert graph_diameter(_dcop([(0, 1), (1, 2), (2, 3)], 4)) == 3
    assert graph_diameter(_dcop([(0, 1), (1, 2), (2, 0)], 3)) == 1
    with pytest.raises(ValueError, match="disconnected"):
        graph_diameter(_dcop([(0, 1)], 4))  # v2, v3 isolated


def test_connected_components():
    comps = connected_components(_dcop([(0, 1), (2, 3)], 5))
    sizes = sorted(len(c) for c in comps)
    assert sizes == [1, 2, 2]


def test_networkx_exports():
    dcop = _dcop([(0, 1), (1, 2)], 3)
    g = as_networkx_graph(dcop)
    assert g.number_of_nodes() == 3
    assert g.number_of_edges() == 2
    fg = as_bipartite_networkx_graph(dcop)
    # 3 variables + 2 constraints, each constraint linked to 2 vars
    assert fg.number_of_nodes() == 5
    assert fg.number_of_edges() == 4
    assert all(
        fg.nodes[n]["bipartite"] == 1 for n in ("c0", "c1")
    )


def test_ternary_constraint_forms_clique_in_primal():
    dcop = DCOP("t")
    vs = [Variable(f"v{i}", D) for i in range(3)]
    for v in vs:
        dcop.add_variable(v)
    dcop.add_constraint(
        constraint_from_str("c0", "v0 + v1 + v2", vs)
    )
    g = as_networkx_graph(dcop)
    assert g.number_of_edges() == 3  # triangle from one ternary scope
    assert has_cycle(dcop)


def test_various_helpers():
    from pydcop_tpu.utils.various import (
        elapsed_str,
        func_args,
        number_format,
    )

    assert func_args(lambda a, b, c=1: 0) == ["a", "b", "c"]
    assert number_format(1500) == "1.5k"
    assert number_format(2.5e6) == "2.5M"
    assert number_format(3) == "3"
    assert elapsed_str(3723) == "1h 02m 03s"
