import pytest

from pydcop_tpu.utils.expressionfunction import ExpressionFunction
from pydcop_tpu.utils.simple_repr import from_repr, simple_repr


def test_simple_expression():
    f = ExpressionFunction("a + b")
    assert set(f.variable_names) == {"a", "b"}
    assert f(a=1, b=2) == 3


def test_conditional_expression():
    f = ExpressionFunction("10 if v1 == v2 else 0")
    assert f(v1="R", v2="R") == 10
    assert f(v1="R", v2="G") == 0


def test_assignment_dict_call():
    f = ExpressionFunction("x * y")
    assert f({"x": 3, "y": 4}) == 12


def test_math_and_builtins_available():
    f = ExpressionFunction("abs(x) + min(y, 2)")
    assert f(x=-1, y=5) == 3
    g = ExpressionFunction("round(math.sqrt(x))")
    assert g(x=9) == 3


def test_multiline_with_return():
    src = "if a > 0:\n    return a * 2\nreturn -a"
    f = ExpressionFunction(src)
    assert set(f.variable_names) == {"a"}
    assert f(a=3) == 6
    assert f(a=-3) == 3


def test_partial_application():
    f = ExpressionFunction("a + b + c")
    g = f.partial(a=10)
    assert set(g.variable_names) == {"b", "c"}
    assert g(b=1, c=2) == 13


def test_fixed_vars_in_ctor():
    f = ExpressionFunction("a + b", b=5)
    assert set(f.variable_names) == {"a"}
    assert f(a=1) == 6


def test_unknown_fixed_var_raises():
    with pytest.raises(ValueError):
        ExpressionFunction("a + b", z=1)


def test_missing_variable_raises():
    f = ExpressionFunction("a + b")
    with pytest.raises(TypeError):
        f(a=1)


def test_round_trip_simple_repr():
    f = ExpressionFunction("a + b", b=2)
    f2 = from_repr(simple_repr(f))
    assert f2(a=1) == 3
    assert f == f2


def test_comprehension_targets_not_free():
    f = ExpressionFunction("sum(i * x for i in [1, 2, 3])")
    assert set(f.variable_names) == {"x"}
    assert f(x=2) == 12


def test_name_containing_return_not_statement_form():
    f = ExpressionFunction("return_delay + 1")
    assert f(return_delay=1) == 2


def test_string_literal_containing_return():
    f = ExpressionFunction("1 if x == 'return' else 0")
    assert f(x="return") == 1
