"""DSA on the batched engine: functional tests on known-optimum problems."""

import jax
import numpy as np
import pytest

from pydcop_tpu.algorithms import (
    AlgorithmDefError,
    load_algorithm_module,
    prepare_algo_params,
)
from pydcop_tpu.api import solve
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import Domain, Variable
from pydcop_tpu.dcop.relations import constraint_from_str
from pydcop_tpu.ops.compile import compile_dcop


def coloring_ring(n=10, colors=3):
    d = Domain("colors", "", list(range(colors)))
    dcop = DCOP(f"ring{n}")
    vs = [Variable(f"v{i}", d) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    for i in range(n):
        j = (i + 1) % n
        dcop.add_constraint(
            constraint_from_str(f"c{i}", f"1 if v{i} == v{j} else 0", vs)
        )
    return dcop


def test_param_validation():
    mod = load_algorithm_module("dsa")
    params = prepare_algo_params({"variant": "A"}, mod.algo_params)
    assert params["variant"] == "A"
    assert params["probability"] == 0.7
    with pytest.raises(AlgorithmDefError):
        prepare_algo_params({"variant": "Z"}, mod.algo_params)
    with pytest.raises(AlgorithmDefError):
        prepare_algo_params({"nope": 1}, mod.algo_params)


def test_dsa_solves_ring_coloring():
    result = solve(coloring_ring(10, 3), "dsa", rounds=150, seed=3)
    assert result["cost"] == 0.0
    # proper coloring
    a = result["assignment"]
    for i in range(10):
        assert a[f"v{i}"] != a[f"v{(i + 1) % 10}"]
    assert result["cycle"] == 150
    assert result["msg_count"] == 150 * 2 * 10  # each var has 2 neighbors
    assert result["status"] == "finished"


@pytest.mark.parametrize("variant", ["A", "B", "C"])
def test_dsa_variants_reduce_cost(variant):
    dcop = coloring_ring(12, 3)
    result = solve(
        dcop, "dsa", {"variant": variant, "probability": 0.5},
        rounds=120, seed=1,
    )
    trace = np.asarray(result["cost_trace"])
    assert result["best_cost" if False else "cost"] <= trace[0]
    assert result["cost"] <= 1.0  # near-optimal on an easy ring


def test_dsa_deterministic_given_seed():
    dcop = coloring_ring(8, 3)
    r1 = solve(dcop, "dsa", rounds=50, seed=7)
    r2 = solve(dcop, "dsa", rounds=50, seed=7)
    assert r1["assignment"] == r2["assignment"]
    assert r1["cost"] == r2["cost"]


def test_dsa_convergence_stop():
    # 2-coloring a path converges quickly and then never changes
    d = Domain("c", "", [0, 1])
    dcop = DCOP("path")
    vs = [Variable(f"v{i}", d) for i in range(4)]
    for v in vs:
        dcop.add_variable(v)
    for i in range(3):
        dcop.add_constraint(
            constraint_from_str(f"c{i}", f"1 if v{i} == v{i+1} else 0", vs)
        )
    result = solve(
        dcop, "dsa", {"variant": "B"}, rounds=5000,
        chunk_size=16, convergence_chunks=2, seed=0,
    )
    assert result["cost"] == 0.0
    assert result["status"] == "converged"
    assert result["cycle"] < 5000


def test_dsa_max_mode():
    # maximize disagreement: optimum = all neighbors different
    d = Domain("c", "", [0, 1, 2])
    dcop = DCOP("max", objective="max")
    vs = [Variable(f"v{i}", d) for i in range(6)]
    for v in vs:
        dcop.add_variable(v)
    for i in range(5):
        dcop.add_constraint(
            constraint_from_str(f"c{i}", f"1 if v{i} != v{i+1} else 0", vs)
        )
    result = solve(dcop, "dsa", rounds=100, seed=0)
    assert result["cost"] == 5.0  # max objective reported in native sign


def test_declared_initial_values():
    d = Domain("c", "", [0, 1, 2])
    dcop = DCOP("init")
    vs = [Variable(f"v{i}", d, initial_value=2) for i in range(4)]
    for v in vs:
        dcop.add_variable(v)
    dcop.add_constraint(constraint_from_str("c", "v0 + v1 + v2 + v3", vs))
    problem = compile_dcop(dcop)
    mod = load_algorithm_module("dsa")
    state = mod.init_state(
        problem, jax.random.PRNGKey(0), {"initial": "declared"}
    )
    assert np.asarray(state["values"]).tolist() == [2, 2, 2, 2]
