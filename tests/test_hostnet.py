"""Cross-process HOST runtime: message-driven agents over TCP
(infrastructure/hostnet.py) — the heterogeneous deployment mode
mirroring the reference's HTTP agents (reference:
``pydcop/infrastructure/communication.py`` HttpCommunicationLayer).
"""

import json
import os
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _ring_yaml(n=8):
    lines = [
        "name: ring",
        "objective: min",
        "domains:",
        "  colors: {values: [0, 1, 2]}",
        "variables:",
    ]
    for i in range(n):
        lines.append(f"  v{i}: {{domain: colors}}")
    lines.append("constraints:")
    for i in range(n):
        j = (i + 1) % n
        lines.append(f"  c{i}:")
        lines.append("    type: intention")
        lines.append(f"    function: 1 if v{i} == v{j} else 0")
    lines.append(f"agents: [{', '.join(f'a{i}' for i in range(n))}]")
    return "\n".join(lines) + "\n"


def _parse_json_tail(text):
    start = text.index("{")
    return json.loads(text[start:])


@pytest.mark.parametrize(
    "algo", ["maxsum", "mgm", "mgm2", "dpop", "syncbb"]
)
def test_host_runtime_two_processes(tmp_path, algo):
    """2 agent processes × N message-driven computations each solve a
    ring to its optimum, messages crossing process boundaries as
    simple_repr JSON over TCP — covering every protocol family: the
    quiescence-terminating factor graph (maxsum), round-synchronized
    budget-terminating local search (mgm, 5-phase mgm2), the
    pseudo-tree UTIL/VALUE waves (dpop), and the ordered-chain bound
    token (syncbb)."""
    yaml_file = tmp_path / "ring.yaml"
    yaml_file.write_text(_ring_yaml())

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["PYDCOP_TPU_PLATFORM"] = "cpu"

    port = 9250 + (os.getpid() % 150)
    orch = subprocess.Popen(
        [
            sys.executable, "-m", "pydcop_tpu", "orchestrator",
            str(yaml_file), "-a", algo, "--runtime", "host",
            "--port", str(port), "--nb_agents", "2", "--rounds", "200",
            "--seed", "3",
        ],
        env=env, cwd=str(tmp_path),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    time.sleep(0.5)
    agents = [
        subprocess.Popen(
            [
                sys.executable, "-m", "pydcop_tpu", "agent",
                "--names", name, "--runtime", "host",
                "--orchestrator", f"localhost:{port}",
            ],
            env=env, cwd=str(tmp_path),
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for name in ("a1", "a2")
    ]
    try:
        orc_out, orc_err = orch.communicate(timeout=120)
        assert orch.returncode == 0, orc_err[-3000:]
        result = _parse_json_tail(orc_out)
        # a ring is 3-colorable: both algorithms find optimum 0 (MGM
        # from this seed; its 1-opt guarantee is asserted elsewhere)
        assert result["cost"] == 0.0
        assert result["status"] in ("finished", "msg_budget")
        assert set(result["assignment"]) == {f"v{i}" for i in range(8)}
        assert sorted(result["agents"]) == ["a1", "a2"]
        # both agents hosted computations and exchanged real messages
        placement = result["placement"]
        assert placement["a1"] and placement["a2"]
        assert result["msg_count"] > 0
        for a in agents:
            a_out, a_err = a.communicate(timeout=30)
            assert a.returncode == 0, a_err[-3000:]
    finally:
        for proc in [orch, *agents]:
            if proc.poll() is None:
                proc.kill()
                proc.communicate()


def test_solve_mode_process_embedding(tmp_path):
    """One-call multi-process embedding (reference:
    run_local_process_dcop / VERDICT r3 missing #2): solve(mode=
    'process') forks local agent OS processes over the TCP host
    runtime and returns the assembled result — here a ring solved to
    its optimum across 3 processes, via the API and the CLI."""
    from pydcop_tpu.api import solve
    from pydcop_tpu.dcop.yamldcop import load_dcop

    dcop = load_dcop(_ring_yaml(9))
    r = solve(
        dcop, "maxsum", mode="process", nb_agents=3, rounds=300,
        timeout=90, seed=1,
    )
    assert r["cost"] == 0.0, r
    assert len(r["agents"]) == 3
    # the dcop's own agent names flow into the placement
    assert set(r["agents"]) <= {f"a{i}" for i in range(9)}
    assert all(r["placement"].values())

    # the CLI surface of the same mode
    yaml_file = tmp_path / "ring9.yaml"
    yaml_file.write_text(_ring_yaml(9))
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["PYDCOP_TPU_PLATFORM"] = "cpu"
    proc = subprocess.run(
        [
            sys.executable, "-m", "pydcop_tpu", "solve",
            str(yaml_file), "-a", "maxsum", "--mode", "process",
            "--nb_agents", "2", "--rounds", "300", "--seed", "1",
        ],
        env=env, cwd=str(tmp_path), capture_output=True, text=True,
        timeout=180,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    result = _parse_json_tail(proc.stdout)
    assert result["cost"] == 0.0
    assert result["status"] in ("finished", "msg_budget")


def test_host_runtime_five_processes_with_strategy(tmp_path):
    """5 agent OS processes, placement computed by a REAL distribution
    strategy (adhoc) over the registered agents, on a 20-variable ring
    — the first above-toy-count deployment (VERDICT r3 next #6).  All
    five agents must host computations, exchange cross-process
    messages, and the run must reach the ring optimum."""
    n = 20
    yaml_file = tmp_path / "ring20.yaml"
    yaml_file.write_text(_ring_yaml(n))

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["PYDCOP_TPU_PLATFORM"] = "cpu"

    port = 9405 + (os.getpid() % 140)
    orch = subprocess.Popen(
        [
            sys.executable, "-m", "pydcop_tpu", "orchestrator",
            str(yaml_file), "-a", "maxsum", "--runtime", "host",
            "--port", str(port), "--nb_agents", "5", "--rounds", "200",
            "--seed", "1", "-d", "adhoc",
        ],
        env=env, cwd=str(tmp_path),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    time.sleep(0.5)
    names = [f"a{i}" for i in range(1, 6)]
    agents = [
        subprocess.Popen(
            [
                sys.executable, "-m", "pydcop_tpu", "agent",
                "--names", name, "--runtime", "host",
                "--orchestrator", f"localhost:{port}",
            ],
            env=env, cwd=str(tmp_path),
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for name in names
    ]
    try:
        orc_out, orc_err = orch.communicate(timeout=180)
        assert orch.returncode == 0, orc_err[-3000:]
        result = _parse_json_tail(orc_out)
        assert result["cost"] == 0.0
        assert sorted(result["agents"]) == names
        placement = result["placement"]
        assert all(placement[a] for a in names), placement
        assert result["msg_count"] > 0
        for a in agents:
            a.communicate(timeout=30)
            assert a.returncode == 0
    finally:
        for proc in [orch, *agents]:
            if proc.poll() is None:
                proc.kill()
                proc.communicate()


def test_tcp_layer_dead_peer_reports_and_raises():
    """A dead destination must (1) surface asynchronously through
    on_send_error — the async writer replaced the old synchronous
    raise — and (2) fail subsequent sends to it fast with
    UnreachableAgent, while count_sent keeps sent >= delivered so the
    two-counter quiescence rule can never fire with frames lost."""
    import socket as _socket

    from pydcop_tpu.infrastructure.communication import UnreachableAgent
    from pydcop_tpu.infrastructure.computations import Message
    from pydcop_tpu.infrastructure.hostnet import TcpCommunicationLayer

    # reserve a port with nothing listening on it
    probe = _socket.socket()
    probe.bind(("127.0.0.1", 0))
    dead_port = probe.getsockname()[1]
    probe.close()

    errors = []
    layer = TcpCommunicationLayer(
        on_send_error=lambda dest, e: errors.append((dest, e))
    )
    try:
        layer.set_addresses({"ghost": ("127.0.0.1", dead_port)})
        layer.send_msg("ghost", "c1", "c2", Message("m", 1))
        deadline = time.time() + 15
        while not errors and time.time() < deadline:
            time.sleep(0.02)
        assert errors and errors[0][0] == "ghost", errors
        with pytest.raises(UnreachableAgent):
            layer.send_msg("ghost", "c1", "c2", Message("m", 2))
        # the lost frame stays counted: sent can only exceed delivered
        assert layer.count_sent == 1
    finally:
        layer.close()


def test_host_runtime_agent_death_fails_cleanly():
    """An agent connection dying mid-solve must fail the orchestrator
    with AgentFailureError promptly — exercised deterministically with
    scripted protocol agents (one keeps reporting busy, one dies after
    start), so no kill-timing race against quiescence."""
    import socket
    import threading

    from pydcop_tpu.dcop.yamldcop import load_dcop
    from pydcop_tpu.infrastructure.hostnet import (
        AgentFailureError,
        run_host_orchestrator,
        _recv,
        _send,
    )

    dcop = load_dcop(_ring_yaml())
    port = 9250 + (os.getpid() % 150) + 2
    outcome = {}

    def orchestrate():
        try:
            run_host_orchestrator(
                dcop, "maxsum", {}, nb_agents=2, port=port,
                rounds=10_000_000, register_timeout=30.0,
            )
            outcome["result"] = "finished"
        except AgentFailureError as e:
            outcome["error"] = str(e)
        except Exception as e:  # pragma: no cover — test diagnostics
            outcome["error"] = f"unexpected {type(e).__name__}: {e}"

    orch = threading.Thread(target=orchestrate, daemon=True)
    orch.start()

    def scripted_agent(name, die_after_polls):
        conn = None
        deadline = time.monotonic() + 20
        while True:
            try:
                conn = socket.create_connection(
                    ("localhost", port), timeout=5
                )
                break
            except OSError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.1)
        reader = conn.makefile("rb")
        _send(conn, {"type": "register", "agent": name, "msg_port": 1})
        dep = _recv(reader)
        assert dep["type"] == "deploy"
        my_vars = [c for c in dep["computations"] if c.startswith("v")]
        _send(conn, {"type": "deployed", "n": len(dep["computations"])})
        polls = 0
        while True:
            msg = _recv(reader)
            if msg is None or msg["type"] == "stop":
                break
            if msg["type"] == "status?":
                polls += 1
                if die_after_polls and polls >= die_after_polls:
                    conn.close()  # mid-solve death
                    return
                # never idle: the run can only end via agent death
                _send(
                    conn,
                    {"type": "status", "idle": False, "delivered": polls},
                )
            elif msg["type"] == "collect":  # anytime-best sampling
                _send(
                    conn,
                    {
                        "type": "result",
                        "values": {v: 0 for v in my_vars},
                        "delivered": polls,
                        "size": polls,
                    },
                )
        conn.close()

    t1 = threading.Thread(
        target=scripted_agent, args=("a1", 3), daemon=True
    )
    t2 = threading.Thread(
        target=scripted_agent, args=("a2", 0), daemon=True
    )
    t0 = time.monotonic()
    t1.start()
    t2.start()
    orch.join(timeout=30)
    assert not orch.is_alive(), "orchestrator hung after agent death"
    assert "died" in outcome.get("error", ""), outcome
    assert time.monotonic() - t0 < 25


def test_host_runtime_placement_and_strategy():
    """Explicit placement maps and distribution-layer strategies both
    drive the host deploy (protocol-level, scripted agents)."""
    import socket
    import threading

    from pydcop_tpu.dcop.yamldcop import load_dcop
    from pydcop_tpu.infrastructure.hostnet import (
        run_host_orchestrator,
        _recv,
        _send,
    )

    dcop = load_dcop(_ring_yaml())
    var_names = [f"v{i}" for i in range(8)]
    want = {
        "a1": var_names[:2] + [f"c{i}" for i in range(8)],
        "a2": var_names[2:],
    }

    def run_with(**kw):
        port = 9250 + (os.getpid() % 150) + 3
        box = {}

        def orchestrate():
            try:
                box["result"] = run_host_orchestrator(
                    dcop, "maxsum", {}, nb_agents=2, port=port,
                    rounds=50, register_timeout=30.0, **kw,
                )
            except Exception as e:
                box["error"] = f"{type(e).__name__}: {e}"

        orch = threading.Thread(target=orchestrate, daemon=True)
        orch.start()
        deploys = {}

        def scripted_agent(name):
            deadline = time.monotonic() + 20
            while True:
                try:
                    conn = socket.create_connection(
                        ("localhost", port), timeout=5
                    )
                    break
                except OSError:
                    if time.monotonic() > deadline:
                        raise
                    time.sleep(0.1)
            reader = conn.makefile("rb")
            _send(
                conn, {"type": "register", "agent": name, "msg_port": 1}
            )
            dep = _recv(reader)
            if not dep or dep.get("type") != "deploy":
                conn.close()  # run failed before deploy (e.g. bad
                return        # placement): end quietly
            deploys[name] = dep
            _send(conn, {"type": "deployed", "n": 0})
            vals = {
                v: 0 for v in dep["computations"] if v.startswith("v")
            }
            while True:
                msg = _recv(reader)
                if msg is None or msg["type"] == "stop":
                    break
                if msg["type"] == "status?":
                    _send(
                        conn,
                        {"type": "status", "idle": True, "delivered": 1},
                    )
                elif msg["type"] == "collect":
                    _send(
                        conn,
                        {
                            "type": "result",
                            "values": vals,
                            "delivered": 1,
                            "size": 1,
                        },
                    )
            conn.close()

        ts = [
            threading.Thread(
                target=scripted_agent, args=(n,), daemon=True
            )
            for n in ("a1", "a2")
        ]
        for t in ts:
            t.start()
        orch.join(timeout=30)
        assert not orch.is_alive()
        return box, deploys

    # explicit placement map is honored exactly
    box, deploys = run_with(placement=want)
    result = box["result"]
    assert sorted(deploys["a1"]["computations"]) == sorted(want["a1"])
    assert sorted(deploys["a2"]["computations"]) == sorted(want["a2"])
    assert result["placement"]["a1"] == sorted(want["a1"])

    # a computation hosted twice is rejected loudly, not solved wrong
    dup = dict(want)
    dup["a2"] = want["a2"] + [want["a1"][0]]
    box, _ = run_with(placement=dup)
    assert "result" not in box and "assigned to both" in box.get(
        "error", ""
    ), box

    # a distribution-layer strategy (adhoc) covers every computation
    box, deploys = run_with(distribution="adhoc")
    result = box["result"]
    all_comps = sorted(
        deploys["a1"]["computations"] + deploys["a2"]["computations"]
    )
    assert all_comps == sorted(
        var_names + [f"c{i}" for i in range(8)]
    )


def test_host_runtime_ui_feed():
    """--uiport on the host orchestrator streams best-cost samples; a
    client sees events during the run and the final status event."""
    import json as jsonmod
    import socket
    import threading
    import urllib.request

    from pydcop_tpu.dcop.yamldcop import load_dcop
    from pydcop_tpu.infrastructure.hostnet import (
        run_host_orchestrator,
        _recv,
        _send,
    )

    def _free_port():
        with socket.socket() as s:
            s.bind(("", 0))
            return s.getsockname()[1]

    dcop = load_dcop(_ring_yaml())
    port = 9250 + (os.getpid() % 150) + 4
    ui_port = _free_port()
    box = {}
    events = []
    ready = threading.Event()

    def client():
        deadline = time.monotonic() + 15
        while True:  # the UI server comes up after agents register
            try:
                req = urllib.request.urlopen(
                    f"http://localhost:{ui_port}/events", timeout=30
                )
                break
            except OSError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.1)
        ready.set()
        for raw in req:
            line = raw.decode().strip()
            if line.startswith("data: "):
                events.append(jsonmod.loads(line[6:]))

    def orchestrate():
        try:
            box["result"] = run_host_orchestrator(
                dcop, "maxsum", {}, nb_agents=1, port=port,
                rounds=5000, register_timeout=30.0, ui_port=ui_port,
                best_sample_period=0.2,
            )
        except Exception as e:
            box["error"] = f"{type(e).__name__}: {e}"

    # SSE client attaches BEFORE the run starts so it sees every event
    t = threading.Thread(target=client, daemon=True)
    orch = threading.Thread(target=orchestrate, daemon=True)

    def scripted_agent():
        deadline = time.monotonic() + 20
        while True:
            try:
                conn = socket.create_connection(
                    ("localhost", port), timeout=5
                )
                break
            except OSError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.1)
        reader = conn.makefile("rb")
        _send(conn, {"type": "register", "agent": "a1", "msg_port": 1})
        dep = _recv(reader)
        vals = {v: 0 for v in dep["computations"] if v.startswith("v")}
        _send(conn, {"type": "deployed", "n": 0})
        t_busy = time.monotonic() + 1.5  # stay busy ~3 sample periods
        while True:
            msg = _recv(reader)
            if msg is None or msg["type"] == "stop":
                break
            if msg["type"] == "status?":
                _send(
                    conn,
                    {
                        "type": "status",
                        "idle": time.monotonic() > t_busy,
                        "delivered": 5,
                    },
                )
            elif msg["type"] == "collect":
                _send(
                    conn,
                    {
                        "type": "result",
                        "values": vals,
                        "delivered": 5,
                        "size": 5,
                    },
                )
        conn.close()

    orch.start()
    t.start()
    threading.Thread(target=scripted_agent, daemon=True).start()
    ready.wait(15)  # client attached (server up => agents registered)
    orch.join(timeout=30)
    assert not orch.is_alive()
    assert "result" in box, box
    t.join(10)
    assert len(events) >= 2  # in-run samples + the final event
    final = events[-1]
    assert final["status"] == "finished"
    assert final["values"] == box["result"]["assignment"]


def _run_sigkill_scenario(
    algo, params, k, n, port_offset, victim="a2", accel=None
):
    """Shared recovery harness: 3 real agent processes, a UI-gated
    SIGKILL of ``victim`` mid-solve, and the recovered result.
    Returns the orchestrator's result dict (asserts the run finished
    with a recorded migration of ``victim``)."""
    import json as _json
    import threading
    import urllib.request

    from pydcop_tpu.dcop.yamldcop import load_dcop
    from pydcop_tpu.infrastructure.hostnet import run_host_orchestrator

    dcop = load_dcop(_ring_yaml(n).replace(
        "agents: [" + ", ".join(f"a{i}" for i in range(n)) + "]",
        "agents: [a1, a2, a3]",
    ))
    assert list(dcop.agents) == ["a1", "a2", "a3"]
    port = 9250 + (os.getpid() % 150) + port_offset
    uiport = port + 40 + port_offset
    box = {}

    def orch():
        try:
            box["result"] = run_host_orchestrator(
                dcop, algo, params, nb_agents=3, port=port,
                rounds=100_000, timeout=60, seed=2, k_target=k,
                ui_port=uiport,
                accel_agents=[accel] if accel else None,
            )
        except Exception as e:  # surfaced by the asserts below
            box["error"] = f"{type(e).__name__}: {e}"

    t = threading.Thread(target=orch, daemon=True)
    t.start()
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["PYDCOP_TPU_PLATFORM"] = "cpu"
    agents = [
        subprocess.Popen(
            [
                sys.executable, "-m", "pydcop_tpu", "agent",
                "--names", f"a{i}", "--runtime", "host",
                "--orchestrator", f"localhost:{port}",
            ],
            env=env, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for i in (1, 2, 3)
    ]
    try:
        # kill only once the run is DEMONSTRABLY underway (a first
        # complete sample reached the UI feed) — killing during agent
        # startup would just fail registration, not test recovery
        deadline = time.monotonic() + 60
        seen = False
        while time.monotonic() < deadline:
            if "error" in box or "result" in box:
                break  # orchestrator ended early: surface it below
            try:
                st = _json.load(
                    urllib.request.urlopen(
                        f"http://127.0.0.1:{uiport}/state", timeout=2
                    )
                )
                if (
                    st.get("events")
                    or st.get("msgs")
                    or st.get("cost") is not None
                ):
                    seen = True
                    break
            except OSError:
                pass
            time.sleep(0.2)
        assert "error" not in box, box["error"]
        assert seen, f"run never produced a first sample ({box})"
        agents[int(victim[1]) - 1].kill()  # SIGKILL mid-solve
        t.join(90)
        assert not t.is_alive(), "orchestrator hung after SIGKILL"
        assert "error" not in box, box["error"]
        r = box["result"]
        # RECOVERED, not failed-cleanly: quiesced with the dead
        # agent's computations re-hosted on survivors
        assert r["status"] == "finished"
        assert r["migrations"], "no migration recorded"
        assert r["migrations"][0]["dead"] == [victim]
        survivors = {"a1", "a2", "a3"} - {victim}
        assert set(r["placement"]) == survivors
        return r
    finally:
        for proc in agents:
            if proc.poll() is None:
                proc.kill()
                proc.communicate()


@pytest.mark.parametrize(
    "algo,params,k,n",
    [
        # DSA converges almost instantly on small local rings, so its
        # case runs a 300-variable ring with a low move probability to
        # guarantee the SIGKILL lands mid-solve (the UI gate below
        # additionally proves the run was underway)
        ("dsa", {"probability": 0.06}, 1, 300),
        ("maxsum", {"damping": 0.5}, 2, 48),
    ],
)
def test_host_runtime_sigkill_recovers_with_replicas(algo, params, k, n):
    """k-resilience on the host runtime (VERDICT r4 next #4): a real
    agent process is SIGKILLed mid-solve and the run RECOVERS — the
    orchestrator solves the reparation DCOP over the live replica
    holders, the orphaned computations migrate (with value restart),
    neighbors re-announce through the on_peer_restarted hook, and the
    run quiesces at the ring's optimum.  k=1 takes the single-candidate
    fast path; k=2 exercises the reparation-DCOP spread across BOTH
    survivors."""
    r = _run_sigkill_scenario(
        algo, params, k, n, port_offset=4 if algo == "dsa" else 6
    )
    assert r["cost"] == 0.0  # quiesced at the ring optimum
    moved = r["migrations"][0]["moved"]
    assert moved, "nothing migrated"
    assert set(moved.values()) <= {"a1", "a3"}


def test_ktarget_rejects_round_barrier_algorithms():
    """k_target migration rebuilds computations at cycle 0, which a
    phased round-barrier protocol would drop as stale and deadlock on
    — the orchestrator rejects the combination at deploy time."""
    from pydcop_tpu.dcop.yamldcop import load_dcop
    from pydcop_tpu.infrastructure.hostnet import (
        PlacementError,
        run_host_orchestrator,
    )

    dcop = load_dcop(_ring_yaml(6))
    with pytest.raises(PlacementError, match="k_target"):
        run_host_orchestrator(
            dcop, "mgm", {}, nb_agents=2, port=19321, k_target=1,
            register_timeout=5.0,
        )


@pytest.mark.parametrize(
    "accel,victim",
    [
        # the ISLAND agent dies: its computations re-deploy as PLAIN
        # host computations on the replica holders (value restart
        # carries the assignment; the compiled pytree dies with the
        # process — docs/cli.md)
        ("a2", "a2"),
        # a PLAIN agent dies while an island SURVIVES: the island must
        # re-announce its boundary values to the migrated computations
        # through on_peer_restarted (a quiescent island has no
        # periodic traffic to re-sync them otherwise)
        ("a1", "a2"),
    ],
)
def test_sigkill_recovery_with_islands(accel, victim):
    """k-resilience × compiled islands, both directions."""
    r = _run_sigkill_scenario(
        "dsa", {"probability": 0.06}, 1, 300,
        port_offset=8 if accel == victim else 10,
        victim=victim, accel=accel,
    )
    assert r["cost"] == 0.0  # quiesced at the ring optimum


def test_solve_k_target_mode_validation():
    """k_target needs killable agent OS processes: solve() rejects it
    for every in-process mode with a pointer to mode='process'."""
    from pydcop_tpu.api import solve
    from pydcop_tpu.dcop.yamldcop import load_dcop

    dcop = load_dcop(_ring_yaml(6))
    for mode in ("batched", "thread", "sim"):
        with pytest.raises(ValueError, match="k_target"):
            solve(dcop, "dsa", mode=mode, k_target=1, timeout=10)
