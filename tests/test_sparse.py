"""Sparse constraint tables (ISSUE 20, ``ops/sparse.py`` +
``ops/semiring.py`` + ``ops/membound.py``, ``docs/performance.md``
'Sparse constraint tables'): the ``table_format`` axis must keep the
idempotent queries BIT-IDENTICAL to the dense path (same argmin
certificate, same host f64 repair), keep the mass queries inside
their reported error bounds (pack truncation folds into the ledger),
compose with ``table_dtype`` and bnb, shrink the memory-bounded
planner's per-node size estimate, and join the service's dispatch
partition key.
"""

from __future__ import annotations

import itertools
import random

import numpy as np
import pytest

from tests.test_semiring import _hard_band_dcop, _random_dcop

pytestmark = pytest.mark.semiring


def _counters(rep):
    return rep.summary()["counters"]


def _infer(dcop, q, fmt, **kw):
    from pydcop_tpu.ops.semiring import run_infer_many
    from pydcop_tpu.telemetry import session

    with session() as rep:
        out = run_infer_many(
            [dcop], q, device="always", table_format=fmt, **kw
        )[0]
    return out, _counters(rep)


# -- packing unit behavior ----------------------------------------------


def test_pack_table_roundtrip_and_gather():
    from pydcop_tpu.ops.sparse import pack_table

    rnd = np.random.default_rng(3)
    a = np.full((8, 8, 8), np.inf)
    finite = rnd.random((8, 8, 8)) < 0.1
    a[finite] = rnd.normal(size=int(finite.sum()))
    sp = pack_table(a, np.inf, min_cells=64)
    assert sp is not None
    assert sp.nnz == int(finite.sum())
    assert sp.density <= 0.5
    assert np.array_equal(np.asarray(sp), a)
    # packed bytes beat the dense box at this sparsity
    assert sp.nbytes < a.size * 4
    # gather hits return values, misses return the fill
    ii, jj, kk = np.nonzero(finite)
    got = sp.gather((ii, jj, kk))
    assert np.array_equal(got, a[ii, jj, kk])
    miss = sp.gather(
        (np.zeros(4, int), np.zeros(4, int), np.zeros(4, int))
    )
    if not finite[0, 0, 0]:
        assert np.all(np.isposinf(miss))


def test_pack_table_declines_dense_or_small():
    from pydcop_tpu.ops.sparse import pack_table

    # too dense: half the cells finite clears max_density only at
    # exactly 0.5 — 60% finite must decline
    a = np.where(
        np.random.default_rng(0).random((8, 8, 8)) < 0.6, 1.0, np.inf
    )
    assert pack_table(a, np.inf, min_cells=64) is None
    # too small: under min_cells the pack overhead cannot pay
    tiny = np.full((4, 4), np.inf)
    tiny[0, 0] = 1.0
    assert pack_table(tiny, np.inf) is None


def test_table_format_vocabulary_suggests_on_typo():
    from pydcop_tpu.ops.sparse import as_table_format

    assert as_table_format(None) == "dense"
    assert as_table_format("coo") == "sparse"
    assert as_table_format("full") == "dense"
    with pytest.raises(ValueError, match="sparse"):
        as_table_format("sprase")


# -- bit parity: idempotent queries -------------------------------------


@pytest.mark.parametrize("ties", [False, True])
@pytest.mark.parametrize("bnb", ["auto", "on"])
@pytest.mark.parametrize("seed", [3, 7])
def test_map_bit_parity(seed, bnb, ties):
    """min-sum MAP: assignment AND cost bit-identical to dense on
    tie-heavy and ±inf (hard-cap) tables, with bnb pruning on."""
    dcop = _hard_band_dcop(10, seed, cap=0.9, ties=ties)
    rd, _ = _infer(dcop, "map", "dense", bnb=bnb)
    rs, cs = _infer(dcop, "map", "sparse", bnb=bnb)
    assert rs["assignment"] == rd["assignment"]
    assert rs["cost"] == rd["cost"]
    assert cs.get("semiring.sparse_nodes", 0) > 0, cs


@pytest.mark.parametrize("dtype", ["bf16", "int8"])
def test_map_bit_parity_low_precision(dtype):
    """format × dtype composition: packed values quantize like dense
    packs and the certificate ladder still repairs exactly."""
    dcop = _hard_band_dcop(10, 3, cap=0.9)
    rd, _ = _infer(dcop, "map", "dense", table_dtype=dtype)
    rs, cs = _infer(dcop, "map", "sparse", table_dtype=dtype)
    assert rs["assignment"] == rd["assignment"]
    assert rs["cost"] == rd["cost"]
    assert cs.get("semiring.sparse_nodes", 0) > 0, cs


def test_max_objective_map_parity():
    """max-sum (fill = -inf on the flipped axis): same contract."""
    dcop = _random_dcop(8, 5, objective="max")
    rd, _ = _infer(dcop, "map", "dense")
    rs, _ = _infer(dcop, "map", "sparse")
    assert rs["assignment"] == rd["assignment"]
    assert rs["cost"] == rd["cost"]


def test_kbest_passthrough_parity():
    """kbest keeps the dense kernels (structured cells never pack):
    sparse must pass through bit-identically, counting a fallback
    instead of corrupting the top-K merge."""
    dcop = _hard_band_dcop(8, 5, cap=0.9)
    rd, _ = _infer(dcop, "kbest:4", "dense")
    rs, cs = _infer(dcop, "kbest:4", "sparse")
    assert rs["costs"] == rd["costs"]
    assert [s["assignment"] for s in rs["solutions"]] == [
        s["assignment"] for s in rd["solutions"]
    ]
    assert cs.get("semiring.sparse_nodes", 0) == 0


# -- mass queries: bounded, monotone ------------------------------------


def test_log_z_within_reported_bound():
    """Device sparse log_z vs exact host f64: the difference must sit
    inside the reported error_bound (pack truncation included)."""
    dcop = _hard_band_dcop(10, 3, cap=0.9)
    rh, _ = _infer(dcop, "log_z", "dense", tol=1e-3)
    rs, cs = _infer(dcop, "log_z", "sparse", tol=1e-3)
    assert abs(rs["log_z"] - rh["log_z"]) <= (
        rs["error_bound"] + rh["error_bound"] + 1e-12
    )
    assert cs.get("semiring.sparse_nodes", 0) > 0, cs


def test_marginals_parity_within_bound():
    dcop = _hard_band_dcop(10, 3, cap=0.9)
    rd, _ = _infer(dcop, "marginals", "dense", tol=1e-3)
    rs, _ = _infer(dcop, "marginals", "sparse", tol=1e-3)
    for v, md in rd["marginals"].items():
        for a, b in zip(md, rs["marginals"][v]):
            assert abs(a - b) <= 1e-3


def test_drop_tol_trunc_is_monotone_and_sound():
    """pack_table's lossy mass packing: the dropped mass is bounded
    by the reported trunc (nats), trunc grows monotonically in
    drop_tol, and drop_tol=0 packs exactly."""
    from pydcop_tpu.ops.sparse import pack_table

    rnd = np.random.default_rng(7)
    a = np.full(4096, -np.inf)
    hot = rnd.random(4096) < 0.2
    a[hot] = rnd.normal(size=int(hot.sum())) * 6.0

    def lse(x):
        f = x[np.isfinite(x)]
        m = f.max()
        return m + np.log(np.exp(f - m).sum())

    exact = lse(a)
    prev_trunc = -1.0
    for tol in (0.0, 1e-9, 1e-6, 1e-3, 1e-1):
        sp = pack_table(
            a, -np.inf, min_cells=64, max_density=0.5, drop_tol=tol
        )
        assert sp is not None
        assert sp.trunc >= prev_trunc  # monotone in drop_tol
        prev_trunc = sp.trunc
        packed = lse(sp.vals)
        # the lost mass is bounded by trunc; packing never ADDS mass
        assert packed <= exact + 1e-12
        assert exact - packed <= sp.trunc + 1e-12
        if tol == 0.0:
            assert sp.trunc == 0.0
            assert packed == exact


# -- memory-bounded planner ---------------------------------------------


@pytest.mark.membound
def test_membound_same_budget_smaller_cut_sparse():
    """The planner sizes hard-capped nodes at their packed estimate:
    the same byte budget needs a no-wider (usually narrower) cut at
    table_format=sparse, and the budgeted result stays bit-identical
    to the unbounded dense solve."""
    from pydcop_tpu.algorithms.dpop import solve_host

    dcop = _hard_band_dcop(12, 3, d=5, arity=5, stride=2, cap=0.9)
    ref = solve_host(dcop, {"util_device": "always"})
    budget = 4096
    rd = solve_host(
        dcop, {"util_device": "always", "max_util_bytes": budget}
    )
    rs = solve_host(
        dcop,
        {
            "util_device": "always",
            "max_util_bytes": budget,
            "table_format": "sparse",
        },
    )
    assert rs["membound"]["cut_width"] <= rd["membound"]["cut_width"]
    assert rs["membound"]["table_format"] == "sparse"
    assert rs["assignment"] == ref["assignment"]
    assert rs["cost"] == ref["cost"]


@pytest.mark.membound
def test_membound_charges_packed_bytes():
    """The membound meta must report a sparse peak no larger than the
    dense peak on a hard-cap workload (the packed estimate)."""
    from pydcop_tpu.algorithms.dpop import solve_host

    dcop = _hard_band_dcop(12, 3, d=5, arity=5, stride=2, cap=0.9)
    kw = {"util_device": "always", "max_util_bytes": 1 << 20}
    rd = solve_host(dcop, kw)
    rs = solve_host(dcop, {**kw, "table_format": "sparse"})
    assert (
        rs["membound"]["peak_table_bytes"]
        <= rd["membound"]["peak_table_bytes"]
    )


# -- memoized sessions ---------------------------------------------------


def test_infer_session_sparse_warm_path():
    """A sparse InferSession stays bit-identical across the memoized
    warm path, and prewarm compiles the sparse-ABI kernels without
    error (the zero-XLA-compile-on-warm-delta guarantee)."""
    from pydcop_tpu.engine.memo import InferSession

    dcop = _hard_band_dcop(8, 7, cap=0.9)
    s = InferSession(dcop, "map", device="always",
                     table_format="sparse")
    cold = s.solve()
    warm = s.solve()
    assert warm["assignment"] == cold["assignment"]
    assert warm["cost"] == cold["cost"]
    assert warm["memo"]["hits"] > 0


# -- gating: engines without a sparse path ------------------------------


def test_iterative_engines_reject_sparse():
    from pydcop_tpu.api import solve
    from pydcop_tpu.ops.compile import compile_dcop

    dcop = _random_dcop(6, 3)
    with pytest.raises(ValueError, match="table_format"):
        solve(dcop, "dsa", {}, rounds=2, table_format="sparse")
    with pytest.raises(ValueError, match="sparse"):
        compile_dcop(dcop, table_format="sparse")


# -- service: format joins the partition key and rides the wire ---------


@pytest.mark.service
def test_service_format_joins_infer_partition_key():
    """Two same-query infers differing ONLY in table_format land in
    one tick but dispatch as TWO partitions — the format is part of
    ``_infer_group_key``, so sparse traffic never merges into a
    dense sweep (or vice versa)."""
    from pydcop_tpu.engine.service import SolverService

    dcop = _hard_band_dcop(8, 1, cap=0.9)
    with SolverService(
        max_batch=2, max_wait=10.0, autostart=False
    ) as svc:
        pd = svc.submit_infer(dcop, "map", device="never")
        ps = svc.submit_infer(
            dcop, "map", device="never", table_format="sparse"
        )
        rd, rs = pd.result(timeout=300), ps.result(timeout=300)
        stats = svc.stats()
    assert rd["cost"] == rs["cost"]
    assert rd["assignment"] == rs["assignment"]
    assert stats["ticks"] == 1, stats
    assert stats["dispatches"] == 2, stats


@pytest.mark.service
def test_service_wire_round_trip_carries_table_format():
    """table_format rides the wire protocol end to end: an infer
    frame and a solve frame both carry it, results match the
    in-process calls bit-for-bit, and a bad spelling fails THIS call
    with the nearest-name suggestion without killing the
    connection."""
    from pydcop_tpu.api import infer
    from pydcop_tpu.dcop.yamldcop import dcop_yaml
    from pydcop_tpu.engine.service import (
        ServiceClient,
        ServiceError,
        ServiceServer,
        SolverService,
    )

    dcop = _hard_band_dcop(8, 1, cap=0.9)
    yaml_text = dcop_yaml(dcop)
    ref = infer(dcop, "map", device="never", table_format="sparse")
    with SolverService(max_wait=0.05) as svc:
        with ServiceServer(svc, port=0) as server:
            with ServiceClient(server.address) as cli:
                out = cli.infer(
                    yaml_text, "map", device="never",
                    table_format="sparse",
                )
                assert out["cost"] == ref["cost"]
                assert out["assignment"] == ref["assignment"]
                s = cli.solve(
                    yaml_text, "dpop", {"util_device": "never"},
                    table_format="sparse",
                )
                assert s["assignment"] == ref["assignment"]
                with pytest.raises(
                    (ServiceError, ValueError), match="sparse"
                ):
                    cli.infer(
                        yaml_text, "map", table_format="sprase"
                    )
                assert cli.ping()  # connection survived the error


if __name__ == "__main__":
    pytest.main([__file__, "-q"])
