"""Supervised device execution (``engine/supervisor.py``).

PR-6 acceptance criteria:

- under chaos ``device_oom`` injection, a K=8 ``solve_many`` group
  completes via group-split with results bit-identical to the
  fault-free run;
- under ``nan_inject`` on one instance, the other K−1 results are
  bit-identical and only the poisoned instance reports
  ``status="degraded"``;
- counters ``engine.oom_splits`` / ``engine.quarantined_instances``
  land in ``result["telemetry"]``;
- a run killed mid-way by ``device_transient`` with an exhausted
  retry budget writes a final checkpoint, and resuming from it gives
  bit-identical final costs vs. an uninterrupted run (crash-resume).

Plus units for the fault-plan device clauses, failure classification,
the keyed deterministic backoff, and the dispatch retry machinery.
"""

import numpy as np
import pytest

from pydcop_tpu.api import solve, solve_many
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
from pydcop_tpu.dcop.relations import constraint_from_str
from pydcop_tpu.engine.supervisor import (
    UNSUPERVISED,
    DeviceOOMError,
    DeviceTransientError,
    Supervisor,
    SupervisorConfig,
    UnrecoverableDeviceError,
    classify_failure,
    get_supervisor,
    supervision,
)
from pydcop_tpu.faults.plan import FaultPlan, FaultSpecError
from pydcop_tpu.utils.backoff import backoff_delays

pytestmark = pytest.mark.supervisor

D = Domain("d", "", [0, 1, 2])


def ring_dcop(n=6):
    dcop = DCOP("ring%d" % n, objective="min")
    vs = [Variable(f"v{i}", D) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    for i in range(n):
        j = (i + 1) % n
        dcop.add_constraint(
            constraint_from_str(
                f"c{i}_{j}", f"1 if v{i} == v{j} else 0", vs
            )
        )
    dcop.add_agents([AgentDef(f"a{i}") for i in range(n)])
    return dcop


# -- fault-plan device clauses ----------------------------------------


def test_device_spec_parses():
    plan = FaultPlan.from_spec(
        "device_oom=16:256,device_transient=0.25:3,nan_inject=0.5:2",
        seed=7,
    )
    d = plan.device
    assert d.oom_width_cap == 16 and d.oom_rounds_cap == 256
    assert d.transient == 0.25 and d.transient_after == 3
    assert d.nan == 0.5 and d.nan_instance == 2
    assert plan.device_faults_configured
    # device kinds are NOT message faults: the host runtimes must not
    # reject a device-only plan as needing a message plane, and vice
    # versa the batched engine must see nothing message-shaped here
    assert not plan.message_faults_configured


def test_device_spec_rounds_only_oom():
    plan = FaultPlan.from_spec("device_oom=-:128", seed=0)
    assert plan.device.oom_width_cap is None
    assert plan.device.oom_rounds_cap == 128
    assert plan.oom_injected(10_000, 64) is False
    assert plan.oom_injected(1, 129) is True


def test_device_spec_rejects_bad_values():
    with pytest.raises(FaultSpecError):
        FaultPlan.from_spec("device_transient=1.5", seed=0)
    with pytest.raises(FaultSpecError):
        FaultPlan.from_spec("nan_inject=x", seed=0)
    with pytest.raises(FaultSpecError):
        FaultPlan.from_spec("device_oom=", seed=0)


def test_device_spec_composes_with_message_clauses():
    plan = FaultPlan.from_spec("drop=0.1,device_oom=8", seed=1)
    assert plan.message_faults_configured
    assert plan.device_faults_configured
    assert plan.to_meta()["spec"] == "drop=0.1,device_oom=8"


def test_oom_capacity_model_is_deterministic():
    """OOM is a capacity model, not a coin flip: the degradation
    ladder converges the moment a re-dispatch fits."""
    plan = FaultPlan.from_spec("device_oom=4", seed=3)
    assert plan.oom_injected(8) and plan.oom_injected(5)
    assert not plan.oom_injected(4) and not plan.oom_injected(1)


def test_transient_decisions_pure_and_seeded():
    a = FaultPlan.from_spec("device_transient=0.5", seed=11)
    b = FaultPlan.from_spec("device_transient=0.5", seed=11)
    c = FaultPlan.from_spec("device_transient=0.5", seed=12)
    seq_a = [a.decide_device_transient("s", i) for i in range(1, 40)]
    seq_b = [b.decide_device_transient("s", i) for i in range(1, 40)]
    seq_c = [c.decide_device_transient("s", i) for i in range(1, 40)]
    assert seq_a == seq_b  # pure in (seed, scope, seq)
    assert seq_a != seq_c
    # AFTER exempts the head of every scope: the "die mid-run" knob
    late = FaultPlan.from_spec("device_transient=1:3", seed=0)
    assert [
        late.decide_device_transient("s", i) for i in range(1, 6)
    ] == [False, False, False, True, True]


# -- failure classification -------------------------------------------


def test_classify_failure():
    assert classify_failure(DeviceOOMError("x")) == "oom"
    assert classify_failure(DeviceTransientError("x")) == "transient"
    assert classify_failure(MemoryError()) == "oom"
    assert (
        classify_failure(RuntimeError("RESOURCE_EXHAUSTED: out of memory"))
        == "oom"
    )
    assert (
        classify_failure(RuntimeError("UNAVAILABLE: socket closed"))
        == "transient"
    )
    # usage errors are fatal — retrying a bug never fixes it
    assert classify_failure(ValueError("bad shape")) == "fatal"


# -- keyed deterministic backoff --------------------------------------


def test_backoff_keyed_is_pure_and_decorrelated():
    take = lambda it, n: [next(it) for _ in range(n)]
    a = take(backoff_delays(seed=5, key="k1"), 6)
    b = take(backoff_delays(seed=5, key="k1"), 6)
    c = take(backoff_delays(seed=5, key="k2"), 6)
    d = take(backoff_delays(seed=6, key="k1"), 6)
    assert a == b  # pure in (seed, key, attempt)
    assert a != c  # distinct keys decorrelate
    assert a != d  # seed matters
    # exponential growth capped at max_delay still holds
    delays = take(
        backoff_delays(base=0.1, factor=2.0, max_delay=0.5,
                       jitter=0.0, seed=0, key="k"), 5,
    )
    assert delays == [0.1, 0.2, 0.4, 0.5, 0.5]


def test_backoff_keyed_interleaving_independence():
    """Two keyed streams give identical schedules no matter how their
    draws interleave — the property the shared-Random variant lacks."""
    s1 = backoff_delays(seed=1, key="a")
    s2 = backoff_delays(seed=1, key="b")
    interleaved_a = []
    interleaved_b = []
    for _ in range(4):  # alternate draws
        interleaved_a.append(next(s1))
        interleaved_b.append(next(s2))
    solo_a = [next(backoff_delays(seed=1, key="a")) for _ in range(1)]
    fresh_a = backoff_delays(seed=1, key="a")
    fresh_b = backoff_delays(seed=1, key="b")
    assert interleaved_a == [next(fresh_a) for _ in range(4)]
    assert interleaved_b == [next(fresh_b) for _ in range(4)]
    assert solo_a[0] == interleaved_a[0]


# -- Supervisor.dispatch ----------------------------------------------


def _sup(spec=None, seed=0, **kw):
    kw.setdefault("sleep", lambda _t: None)  # no real sleeping in tests
    plan = FaultPlan.from_spec(spec, seed) if spec else None
    return Supervisor(SupervisorConfig(plan=plan, **kw))


def test_dispatch_retries_transient_then_succeeds():
    sup = _sup(retry_budget=3)
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise DeviceTransientError("blip")
        return "ok"

    assert sup.dispatch(flaky) == "ok"
    assert len(calls) == 3


def test_dispatch_exhausts_budget():
    sup = _sup(retry_budget=2)
    with pytest.raises(UnrecoverableDeviceError) as ei:
        sup.dispatch(lambda: (_ for _ in ()).throw(
            DeviceTransientError("always")
        ))
    assert ei.value.kind == "transient"
    assert ei.value.attempts == 2


def test_dispatch_oom_always_surfaces():
    """OOM never retries in place — degradation is the caller's move."""
    sup = _sup(retry_budget=5)
    calls = []

    def boom():
        calls.append(1)
        raise RuntimeError("RESOURCE_EXHAUSTED: could not allocate")

    with pytest.raises(DeviceOOMError):
        sup.dispatch(boom)
    assert len(calls) == 1


def test_dispatch_fatal_reraises_original():
    sup = _sup(retry_budget=5)
    with pytest.raises(ValueError, match="shape"):
        sup.dispatch(lambda: (_ for _ in ()).throw(ValueError("shape")))


def test_dispatch_injects_from_plan():
    sup = _sup("device_oom=4", seed=1)
    with pytest.raises(DeviceOOMError):
        sup.dispatch(lambda: "ran", width=8)
    assert sup.dispatch(lambda: "ran", width=4) == "ran"


def test_injected_transient_retry_draws_fresh_seq():
    """Retries draw fresh sequence numbers, so P<1 lets one through
    (seed 0: seq1 fails, seq2 passes for this scope)."""
    plan = FaultPlan.from_spec("device_transient=0.5", 0)
    decisions = [
        plan.decide_device_transient("engine.chunk", s)
        for s in range(1, 6)
    ]
    assert True in decisions and False in decisions
    sup = _sup("device_transient=0.5", seed=0, retry_budget=5)
    assert sup.dispatch(lambda: "ok", scope="engine.chunk") == "ok"


def test_supervision_context_and_default():
    default = get_supervisor()
    assert default.active and default.plan is None
    mine = _sup(retry_budget=9)
    with supervision(mine):
        assert get_supervisor() is mine
    assert get_supervisor() is default
    assert UNSUPERVISED.dispatch(lambda: 42) == 42
    assert UNSUPERVISED.nan_lanes(8) == []


def test_config_validation():
    with pytest.raises(ValueError):
        SupervisorConfig(retry_budget=-1)
    with pytest.raises(ValueError):
        SupervisorConfig(chunk_floor=0)
    with pytest.raises(ValueError):
        SupervisorConfig(on_numeric_fault="explode")


# -- engine recovery paths (the acceptance criteria) -------------------


def test_solve_many_oom_group_split_bit_identical():
    """K=8 group under device_oom: completes via group-split, results
    bit-identical to the fault-free run, engine.oom_splits counted."""
    dcops = [ring_dcop(5 + i % 3) for i in range(8)]
    kw = dict(rounds=24, chunk_size=12, pad_policy="pow2:16", seed=7)
    base = solve_many(dcops, "mgm", **kw)
    oom = solve_many(
        dcops, "mgm", chaos="device_oom=4", chaos_seed=3, **kw
    )
    for b, o in zip(base, oom):
        assert o["status"] == "finished"
        assert b["assignment"] == o["assignment"]
        assert b["cost"] == o["cost"]
        assert b["cost_trace"] == o["cost_trace"]
    counters = oom[0]["telemetry"]["counters"]
    assert counters["engine.oom_splits"] >= 1
    assert counters["fault.device_oom"] >= 1
    assert counters["engine.instances_batched"] == 8
    assert oom[0]["chaos"] == {"spec": "device_oom=4", "seed": 3}


def test_solve_many_oom_recursive_split_to_singles():
    """A width cap of 1 forces splits all the way down to single-lane
    groups — still bit-identical, one split per level of the tree."""
    dcops = [ring_dcop(6) for _ in range(4)]
    kw = dict(rounds=12, chunk_size=12, pad_policy="pow2:16", seed=1)
    base = solve_many(dcops, "mgm", **kw)
    oom = solve_many(
        dcops, "mgm", chaos="device_oom=1", chaos_seed=0, **kw
    )
    for b, o in zip(base, oom):
        assert b["cost"] == o["cost"]
        assert b["assignment"] == o["assignment"]
    counters = oom[0]["telemetry"]["counters"]
    assert counters["engine.oom_splits"] == 3  # 4 -> 2+2 -> 1+1+1+1


def test_solve_many_nan_quarantine_spares_the_group():
    """nan_inject on lane 2: the other K-1 results bit-identical, only
    the poisoned instance degraded, counter in result telemetry."""
    dcops = [ring_dcop(5 + i % 3) for i in range(8)]
    kw = dict(rounds=24, chunk_size=12, pad_policy="pow2:16", seed=7)
    base = solve_many(dcops, "mgm", **kw)
    nan = solve_many(
        dcops, "mgm", chaos="nan_inject=1:2", chaos_seed=3, **kw
    )
    statuses = [r["status"] for r in nan]
    assert statuses.count("degraded") == 1 and statuses[2] == "degraded"
    for i, (b, o) in enumerate(zip(base, nan)):
        if i != 2:
            assert b["assignment"] == o["assignment"]
            assert b["cost"] == o["cost"]
            assert b["cost_trace"] == o["cost_trace"]
    # the degraded lane reports its last-finite anytime best, finite
    assert np.isfinite(nan[2]["cost"])
    counters = nan[0]["telemetry"]["counters"]
    assert counters["engine.quarantined_instances"] == 1
    assert counters["fault.nan_inject"] >= 1


def test_solve_many_numeric_fault_raise_mode():
    dcops = [ring_dcop(6) for _ in range(3)]
    with pytest.raises(UnrecoverableDeviceError) as ei:
        solve_many(
            dcops, "mgm", rounds=12, chunk_size=12,
            pad_policy="pow2:16", chaos="nan_inject=1:1",
            chaos_seed=0, on_numeric_fault="raise",
        )
    assert ei.value.kind == "numeric"


def test_solve_transient_retry_parity():
    """Transient blips under the retry budget leave the result
    bit-identical (the retry fast path re-dispatches the same chunk)."""
    base = solve(
        ring_dcop(), "dsa", rounds=48, chunk_size=12, seed=3,
        mode="batched",
    )
    r = solve(
        ring_dcop(), "dsa", rounds=48, chunk_size=12, seed=3,
        mode="batched", chaos="device_transient=0.5", chaos_seed=3,
        retry_budget=4,
    )
    assert r["status"] == base["status"]
    assert r["cost"] == base["cost"]
    assert r["assignment"] == base["assignment"]
    assert r["cost_trace"] == base["cost_trace"]
    assert r["telemetry"]["counters"]["engine.retries"] >= 1


def test_solve_oom_chunk_halving():
    """A rounds-cap OOM halves the chunk until dispatches fit; a
    deterministic algorithm's result is unchanged."""
    base = solve(
        ring_dcop(), "mgm", rounds=48, chunk_size=48, seed=3,
        mode="batched",
    )
    r = solve(
        ring_dcop(), "mgm", rounds=48, chunk_size=48, seed=3,
        mode="batched", chaos="device_oom=-:16", chaos_seed=0,
        chunk_floor=4,
    )
    assert r["status"] == "finished"
    assert r["cost"] == base["cost"]
    assert r["assignment"] == base["assignment"]
    assert (
        r["telemetry"]["counters"]["engine.oom_chunk_halvings"] >= 1
    )


def test_solve_oom_below_floor_unrecoverable(tmp_path):
    """chunk_floor stops the ladder: a capacity no chunk fits is a
    genuine over-capacity failure — with a final checkpoint written."""
    ck = str(tmp_path / "final.npz")
    with pytest.raises(UnrecoverableDeviceError) as ei:
        solve(
            ring_dcop(), "mgm", rounds=48, chunk_size=16, seed=3,
            mode="batched", chaos="device_oom=-:1", chaos_seed=0,
            chunk_floor=8, checkpoint_path=ck, checkpoint_every=999,
        )
    assert ei.value.kind == "oom"
    import os

    assert os.path.exists(ck)  # the supervisor's final checkpoint


def test_solve_nan_quarantine_single_run_degrades():
    r = solve(
        ring_dcop(), "dsa", rounds=48, chunk_size=12, seed=3,
        mode="batched", chaos="nan_inject=1", chaos_seed=0,
    )
    assert r["status"] == "degraded"
    assert np.isfinite(r["cost"])
    assert r["telemetry"]["counters"]["engine.numeric_faults"] >= 1


def test_dpop_level_oom_falls_back_exactly():
    """DPOP level sweeps under a width cap degrade to per-node (and
    per-node OOM to host f64) with bit-identical exact results."""
    base = solve(ring_dcop(8), "dpop", mode="batched")
    oom = solve(
        ring_dcop(8), "dpop", mode="batched", chaos="device_oom=1",
        chaos_seed=0,
    )
    assert oom["cost"] == base["cost"]
    assert oom["assignment"] == base["assignment"]


def test_supervisor_knobs_rejected_off_batched():
    with pytest.raises(ValueError, match="supervised"):
        solve(ring_dcop(3), "mgm", mode="thread", retry_budget=1)


def test_solve_many_rejects_message_plane_chaos():
    with pytest.raises(ValueError, match="DEVICE-layer"):
        solve_many([ring_dcop(3)], "mgm", chaos="drop=0.5")


# -- donated dispatches: real (post-sync) failures --------------------
#
# Injected faults fire BEFORE the wrapped call, so the carry buffers
# are intact and in-place retry is sound.  A REAL failure surfaces at
# the sync point — after a donate=True dispatch consumed its carries —
# so recovery must never re-call the closure; it restarts the group
# from round 0 off the intact host-side stacks instead.  Simulated by
# poisoning the warm runner cache to fail once with a real-looking
# runtime error.


def _poison_runner_cache_once(error_text):
    """Wrap every cached runner to raise ``error_text`` on its first
    call, then delegate.  Returns a restore() callable."""
    from pydcop_tpu.engine import batched

    saved = dict(batched._RUNNER_CACHE)

    def _wrap(runner):
        fired = []

        def inner(*a, **k):
            if not fired:
                fired.append(1)
                raise RuntimeError(error_text)
            return runner(*a, **k)

        return inner

    for key, runner in list(batched._RUNNER_CACHE.items()):
        batched._RUNNER_CACHE[key] = _wrap(runner)

    def restore():
        batched._RUNNER_CACHE.clear()
        batched._RUNNER_CACHE.update(saved)

    return restore


def test_dispatch_not_retryable_hands_back_transient():
    """retryable=False: a real transient must NOT re-call fn (its
    donated inputs are consumed) — it surfaces for a caller restart."""
    sup = _sup(retry_budget=3)
    calls = []

    def boom():
        calls.append(1)
        raise RuntimeError("UNAVAILABLE: socket closed")

    with pytest.raises(DeviceTransientError):
        sup.dispatch(boom, retryable=False)
    assert len(calls) == 1


def test_injected_transient_retries_in_place_when_not_retryable():
    """Injected transients fire BEFORE fn runs, so they retry in
    place even for donated (retryable=False) dispatches — and fn
    still runs exactly once."""
    sup = _sup("device_transient=0.5", seed=0, retry_budget=5)
    calls = []

    def ok():
        calls.append(1)
        return "ok"

    assert (
        sup.dispatch(ok, scope="engine.chunk", retryable=False) == "ok"
    )
    assert len(calls) == 1


def test_solve_many_real_transient_with_donation_restarts():
    """A real transient on a donated group dispatch recovers via
    whole-group restart, bit-identical to the fault-free run."""
    dcops = [ring_dcop(5 + i % 3) for i in range(4)]
    kw = dict(rounds=24, chunk_size=12, pad_policy="pow2:16", seed=7)
    base = solve_many(dcops, "mgm", **kw)  # also warms the cache
    restore = _poison_runner_cache_once("UNAVAILABLE: link blipped")
    try:
        r = solve_many(dcops, "mgm", **kw)
    finally:
        restore()
    for b, o in zip(base, r):
        assert o["status"] == "finished"
        assert b["cost"] == o["cost"]
        assert b["assignment"] == o["assignment"]
        assert b["cost_trace"] == o["cost_trace"]
    assert r[0]["telemetry"]["counters"]["engine.retries"] >= 1


def test_solve_many_real_oom_single_lane_restarts_halved():
    """A real OOM on a donated single-lane group restarts from round
    0 at the halved chunk instead of reusing the consumed carries."""
    dcops = [ring_dcop(6)]
    kw = dict(rounds=24, chunk_size=24, pad_policy="pow2:16", seed=7)
    base = solve_many(dcops, "mgm", **kw)
    restore = _poison_runner_cache_once(
        "RESOURCE_EXHAUSTED: out of memory allocating"
    )
    try:
        r = solve_many(dcops, "mgm", **kw)
    finally:
        restore()
    assert r[0]["status"] == "finished"
    assert r[0]["cost"] == base[0]["cost"]
    assert r[0]["assignment"] == base[0]["assignment"]
    counters = r[0]["telemetry"]["counters"]
    assert counters["engine.oom_chunk_halvings"] >= 1


def test_run_dynamic_propagates_degraded():
    """A NaN-quarantined segment must mark the WHOLE dynamic run
    degraded (sticky), not report status='finished'."""
    from pydcop_tpu.dcop.scenario import Scenario
    from pydcop_tpu.engine.dynamic import run_dynamic

    plan = FaultPlan.from_spec("nan_inject=1", 0)
    sup = Supervisor(
        SupervisorConfig(plan=plan, sleep=lambda _t: None)
    )
    with supervision(sup):
        r = run_dynamic(
            ring_dcop(), "dsa", {"variant": "B"},
            scenario=Scenario([]), k_target=0, final_rounds=24,
            chunk_size=12, seed=3,
        )
    assert r["status"] == "degraded"


def test_host_mode_rejects_device_chaos():
    """Device-layer chaos on a host runtime would silently no-op —
    it must be rejected, mirroring the batched engine's rejection of
    message-plane kinds."""
    with pytest.raises(ValueError, match="device dispatch"):
        solve(ring_dcop(3), "mgm", mode="thread", chaos="device_oom=4")


# -- crash-resume (satellite) -----------------------------------------


def test_crash_resume_bit_identical(tmp_path):
    """Kill a run mid-way (device_transient with exhausted budget),
    resume from the supervisor's final checkpoint, and the final
    costs are bit-identical to an uninterrupted run."""
    ck = str(tmp_path / "crash.npz")
    kw = dict(rounds=48, chunk_size=12, seed=3, mode="batched")
    base = solve(ring_dcop(), "dsa", **kw)
    # P=1 after the 2nd dispatch: chunks 1-2 run, chunk 3 dies on
    # every attempt; checkpoint_every is huge so the ONLY checkpoint
    # is the supervisor's final write before surfacing the error
    with pytest.raises(UnrecoverableDeviceError):
        solve(
            ring_dcop(), "dsa", checkpoint_path=ck,
            checkpoint_every=999, chaos="device_transient=1:2",
            chaos_seed=0, retry_budget=1, **kw,
        )
    resumed = solve(
        ring_dcop(), "dsa", checkpoint_path=ck, resume=True, **kw
    )
    assert resumed["status"] == "finished"
    assert resumed["cycle"] == base["cycle"] == 48
    assert resumed["cost"] == base["cost"]
    assert resumed["final_cost"] == base["final_cost"]
    assert resumed["assignment"] == base["assignment"]
    # the resumed trace covers rounds 24..48; it must equal the tail
    # of the uninterrupted run's trace bit-for-bit (same fold_in-by-
    # absolute-round RNG stream)
    n = len(resumed["cost_trace"])
    assert resumed["cost_trace"] == base["cost_trace"][-n:]
