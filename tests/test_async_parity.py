"""Async-semantics parity: host message-driven runtime vs batched engine.

VERDICT r1 item 6: A-DSA / A-Max-Sum on the batched engine are schedule
variants (per-edge Bernoulli activation); these tests anchor them to an
INDEPENDENT implementation — the host message-driven computations of
``algorithms/_host_dsa.py`` / ``_host_maxsum.py`` running on the seeded
async event loop (``infrastructure.runtime``, ``mode='sim'``), which
share no math with the batched kernels.

Parity claim tested distributionally: on a random coloring problem both
executions reach final/anytime costs of the same quality — far below
the random-assignment baseline and within a small absolute band of each
other.  (Exact per-seed equality is not expected: the schedules differ
by construction.)
"""

import random

import numpy as np
import pytest

from pydcop_tpu.algorithms import load_algorithm_module, prepare_algo_params
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import Domain, Variable
from pydcop_tpu.dcop.relations import NAryMatrixRelation
from pydcop_tpu.engine.batched import run_batched
from pydcop_tpu.infrastructure import solve_host
from pydcop_tpu.ops import compile_dcop

N_SEEDS = 6
MAX_MSGS = 20_000
ROUNDS = 200


def coloring_dcop(n=15, colors=3, degree=3, seed=0):
    rnd = random.Random(seed)
    D = Domain("colors", "", list(range(colors)))
    dcop = DCOP("col")
    vs = [Variable(f"v{i}", D) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    eq = np.eye(colors)
    seen = set()
    cid = 0
    for i in range(n):
        for _ in range(degree):
            j = rnd.randrange(n)
            if i == j or (min(i, j), max(i, j)) in seen:
                continue
            seen.add((min(i, j), max(i, j)))
            dcop.add_constraint(
                NAryMatrixRelation([vs[i], vs[j]], eq, name=f"c{cid}")
            )
            cid += 1
    return dcop


@pytest.fixture(scope="module")
def instance():
    dcop = coloring_dcop()
    return dcop, compile_dcop(dcop)


def _random_baseline(dcop):
    """Expected cost of a uniform random assignment: one violation per
    constraint with probability 1/colors."""
    return len(dcop.constraints) / 3.0


@pytest.mark.parametrize(
    "algo,params",
    [
        ("amaxsum", {}),
        ("adsa", {}),  # variant B default
        ("adsa", {"variant": "A"}),
    ],
)
def test_host_async_vs_batched_cost_distribution(instance, algo, params):
    dcop, problem = instance
    host_costs = [
        solve_host(
            dcop, algo, params, mode="sim", seed=s, max_msgs=MAX_MSGS
        )["cost"]
        for s in range(N_SEEDS)
    ]
    module = load_algorithm_module(algo)
    full = prepare_algo_params(params, module.algo_params)
    batched_costs = [
        run_batched(
            problem, module, full, rounds=ROUNDS, seed=s, chunk_size=64
        ).best_cost
        for s in range(N_SEEDS)
    ]
    baseline = _random_baseline(dcop)
    host_mean = float(np.mean(host_costs))
    batched_mean = float(np.mean(batched_costs))
    # both engines solve the problem (clearly below random assignment)
    assert host_mean < baseline / 2, (host_costs, baseline)
    assert batched_mean < baseline / 2, (batched_costs, baseline)
    # and their quality distributions sit in the same band
    assert abs(host_mean - batched_mean) <= 3.0, (
        host_costs,
        batched_costs,
    )


def test_host_sync_maxsum_matches_batched_on_tree():
    """On a tree both derivations must be EXACT, not just comparable."""
    D = Domain("colors", "", [0, 1, 2])
    dcop = DCOP("tree")
    vs = [Variable(f"v{i}", D) for i in range(9)]
    for v in vs:
        dcop.add_variable(v)
    eq = np.eye(3)
    for i in range(1, 9):
        p = (i - 1) // 2
        dcop.add_constraint(
            NAryMatrixRelation([vs[i], vs[p]], eq, name=f"c{i}")
        )
    problem = compile_dcop(dcop)
    module = load_algorithm_module("maxsum")
    params = prepare_algo_params({}, module.algo_params)
    for s in range(3):
        host = solve_host(dcop, "maxsum", mode="sim", seed=s)
        batched = run_batched(
            problem, module, params, rounds=60, seed=s, chunk_size=30
        )
        assert host["cost"] == 0, host
        assert batched.best_cost == 0, batched


def test_host_mgm_reaches_local_optimum():
    """The message-driven MGM (round-synchronized value/gain phases,
    _host_mgm.py) must end 1-opt locally optimal: no single variable
    can improve the assignment — MGM's convergence guarantee."""
    import __graft_entry__ as g
    from pydcop_tpu.infrastructure import solve_host

    for mode in ("sim", "thread"):
        dcop = g._make_coloring_dcop(24, degree=2, seed=3)
        r = solve_host(dcop, "mgm", {}, mode=mode, rounds=400, timeout=30)
        final = r["final_assignment"]
        base = dcop.solution_cost(final)
        for name, var in dcop.variables.items():
            for val in var.domain.values:
                if val == final[name]:
                    continue
                mod = dict(final)
                mod[name] = val
                assert dcop.solution_cost(mod) >= base - 1e-6, (
                    mode, name, val,
                )


def test_host_mgm_isolated_variable_settles_unary_best():
    """An unconstrained variable has no message-driven phases; MGM must
    still settle its best unary value (code-review r3 finding)."""
    from pydcop_tpu.dcop.dcop import DCOP
    from pydcop_tpu.dcop.objects import Domain, VariableWithCostDict
    from pydcop_tpu.infrastructure import solve_host

    d = Domain("d", "", [0, 1, 2])
    dcop = DCOP("iso")
    dcop.add_variable(
        VariableWithCostDict("x", d, {0: 0.0, 1: 5.0, 2: 5.0})
    )
    r = solve_host(dcop, "mgm", {}, mode="sim", rounds=20, timeout=10)
    assert r["final_assignment"]["x"] == 0
    assert r["final_cost"] == 0.0


def test_host_dba_breaks_out_of_local_minimum():
    """Message-driven DBA (_host_dba.py): the weight-increase breakout
    must escape the local optimum MGM gets stuck in on the same
    instance — ending conflict-free (cost = noise only)."""
    import __graft_entry__ as g
    from pydcop_tpu.infrastructure import solve_host

    dcop = g._make_coloring_dcop(24, degree=2, seed=3)
    r_mgm = solve_host(dcop, "mgm", {}, mode="sim", rounds=400, timeout=30)
    r_dba = solve_host(dcop, "dba", {}, mode="sim", rounds=400, timeout=30)
    # the coloring penalty per conflict is 1; noise sums to < 0.5
    assert r_mgm["cost"] > 1.0  # MGM: stuck with >= 1 conflict
    assert r_dba["cost"] < 0.5  # DBA: broke out, zero conflicts


def test_host_mgm2_cost_distribution_matches_batched():
    """Message-driven MGM-2 (_host_mgm2.py, 5 synchronized phases) and
    the batched one-jitted-step engine share semantics; their final
    cost distributions must sit in the same band on the same seeds."""
    import __graft_entry__ as g
    from pydcop_tpu.infrastructure import solve_host

    dcop = g._make_coloring_dcop(24, degree=2, seed=3)
    problem = compile_dcop(dcop)
    module = load_algorithm_module("mgm2")
    params = prepare_algo_params({}, module.algo_params)
    batched = [
        run_batched(
            problem, module, params, rounds=200, seed=s, chunk_size=64
        ).best_cost
        for s in range(N_SEEDS)
    ]
    host = [
        solve_host(
            dcop, "mgm2", {}, mode="sim", seed=s, max_msgs=MAX_MSGS,
            timeout=30,
        )["cost"]
        for s in range(N_SEEDS)
    ]
    baseline = len(dcop.constraints) / 3.0
    assert float(np.mean(host)) < baseline / 2, host
    assert abs(float(np.mean(host)) - float(np.mean(batched))) <= 3.0, (
        host,
        batched,
    )


def test_host_mgm2_pair_move_escapes_mgm_minimum():
    """The coordinated pair move is MGM-2's whole point: on a 2-variable
    instance whose optimum (1,1) is unreachable by unilateral moves
    from (0,0), MGM must stay stuck and MGM-2 must coordinate the
    joint move — in both sim and thread modes."""
    from pydcop_tpu.infrastructure import solve_host

    D = Domain("b", "", [0, 1])
    table = np.array([[0.5, 2.0], [2.0, 0.0]])
    for mode in ("sim", "thread"):
        dcop = DCOP("pair")
        x = Variable("x", D, initial_value=0)
        y = Variable("y", D, initial_value=0)
        dcop.add_variable(x)
        dcop.add_variable(y)
        dcop.add_constraint(NAryMatrixRelation([x, y], table, name="c"))
        r_mgm = solve_host(
            dcop, "mgm", {"initial": "declared"}, mode=mode,
            rounds=60, timeout=20,
        )
        r_mgm2 = solve_host(
            dcop, "mgm2", {"initial": "declared"}, mode=mode,
            rounds=200, timeout=30,
        )
        assert r_mgm["final_cost"] == 0.5, (mode, r_mgm)  # stuck
        assert r_mgm2["final_cost"] == 0.0, (mode, r_mgm2)  # escaped
        assert r_mgm2["final_assignment"] == {"x": 1, "y": 1}


def test_host_dpop_and_syncbb_are_exact():
    """The message-driven DPOP (UTIL/VALUE waves) and SyncBB (bound
    token walk) must reproduce the production engines' exact optimum
    and terminate by quiescence."""
    import __graft_entry__ as g
    from pydcop_tpu.algorithms import dpop as dpop_mod
    from pydcop_tpu.algorithms import syncbb as syncbb_mod
    from pydcop_tpu.infrastructure import solve_host

    for seed in range(3):
        dcop = g._make_coloring_dcop(12, degree=2, seed=seed)
        exact = dpop_mod.solve_host(dcop, {})
        bb = syncbb_mod.solve_host(dcop, {})
        assert abs(exact["cost"] - bb["cost"]) < 1e-9
        for algo in ("dpop", "syncbb"):
            for mode in ("sim", "thread"):
                r = solve_host(
                    dcop, algo, {}, mode=mode, timeout=60,
                    max_msgs=500_000,
                )
                assert r["status"] == "finished", (algo, mode, r)
                assert abs(r["final_cost"] - exact["cost"]) < 1e-9, (
                    algo, mode, seed, r["final_cost"], exact["cost"],
                )


def test_host_gdba_breaks_out_and_syncs_weights():
    """Message-driven GDBA (_host_gdba.py): the cell-targeted increase
    modes (E/R/C) escape the local minimum, and endpoint copies of the
    per-cell weight tables stay identical (the flags carry explicit
    cell lists, applied additively like the batched delta)."""
    import time

    import __graft_entry__ as g
    from pydcop_tpu.algorithms import (
        load_algorithm_module,
        prepare_algo_params,
    )
    from pydcop_tpu.infrastructure import solve_host
    from pydcop_tpu.infrastructure.runtime import (
        _build_computations,
        _run_sim,
    )

    dcop = g._make_coloring_dcop(24, degree=2, seed=3)
    # the same instance MGM stays stuck on (test_host_dba_breaks_out)
    r_mgm = solve_host(dcop, "mgm", {}, mode="sim", rounds=400, timeout=30)
    assert r_mgm["cost"] > 1.0
    for imode in ("E", "R", "C"):
        r = solve_host(
            dcop, "gdba", {"increase_mode": imode}, mode="sim",
            rounds=400, timeout=30,
        )
        assert r["cost"] < 0.5, (imode, r["cost"])  # conflict-free

    module = load_algorithm_module("gdba")
    params = prepare_algo_params({}, module.algo_params)
    comps, _ = _build_computations(dcop, "gdba", params, seed=0)
    # t0 is a perf_counter() origin — 0.0 would trip the timeout on
    # the first delivery and run zero messages (round-3 bug)
    _run_sim(comps, 30.0, 40_000, 0, time.perf_counter(), lambda *a: None)
    final = {c.name: c.current_value for c in comps}
    assert dcop.solution_cost(final) < 0.5  # escaped the minimum
    tables = {}
    for comp in comps:
        for cname, wt in comp._weights.items():
            key = tuple(sorted(wt.items()))
            tables.setdefault(cname, set()).add(key)
    assert all(len(v) == 1 for v in tables.values())
    # breakout actually fired somewhere
    assert any(wt for comp in comps for wt in comp._weights.values())
