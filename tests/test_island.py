"""Compiled-island Max-Sum — the heterogeneous strong-host deployment
(``algorithms/_island_maxsum.py``): one agent's factor-graph subgraph
runs on the array engine behind per-node proxies while other agents run
plain host computations; boundary messages stay MaxSumCostMessage
frames, so the mix is invisible on the wire.

Reference analogue: pyDcop deploys heterogeneous agents over HTTP
(``pydcop/infrastructure/communication.py``); the island is this
build's TPU-first version — the machine with the chip hosts its whole
sub-problem as one compiled island.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _chain_dcop(n=6, colors=3):
    """A path v0-v1-...-v{n-1} with equality-penalty constraints: a
    TREE, so min-sum converges to the exact optimum (0)."""
    from pydcop_tpu.dcop.dcop import DCOP
    from pydcop_tpu.dcop.objects import Domain, Variable
    from pydcop_tpu.dcop.relations import NAryMatrixRelation

    d = Domain("colors", "", list(range(colors)))
    dcop = DCOP("chain", objective="min")
    vs = [Variable(f"v{i}", d) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    eye = np.eye(colors)
    for i in range(n - 1):
        dcop.add_constraint(
            NAryMatrixRelation([vs[i], vs[i + 1]], eye, name=f"c{i}")
        )
    return dcop


def _graph_and_defs(dcop, params=None, algo="maxsum"):
    from pydcop_tpu.algorithms import (
        AlgorithmDef,
        ComputationDef,
        load_algorithm_module,
        prepare_algo_params,
    )
    from pydcop_tpu.graphs import load_graph_module

    module = load_algorithm_module(algo)
    params = prepare_algo_params(params or {}, module.algo_params)
    graph = load_graph_module(module.GRAPH_TYPE).build_computation_graph(
        dcop
    )
    algo_def = AlgorithmDef(algo, params, dcop.objective)
    defs = {
        n.name: ComputationDef(n, algo_def) for n in graph.nodes
    }
    return module, defs


def _cost(dcop, comps):
    from pydcop_tpu.infrastructure.computations import (
        VariableComputation,
    )

    assignment = {
        c.variable.name: c.current_value
        for c in comps
        if isinstance(c, VariableComputation)
    }
    assert None not in assignment.values(), assignment
    return dcop.solution_cost(assignment), assignment


def test_island_pure():
    """Whole problem on one island: the start burst alone must solve a
    tree to its optimum (no boundary traffic exists)."""
    from pydcop_tpu.algorithms import maxsum

    dcop = _chain_dcop(8)
    module, defs = _graph_and_defs(dcop)
    comps = maxsum.build_island(list(defs.values()), dcop, seed=1)
    # every graph node got a proxy (routing/collect surface intact)
    assert {c.name for c in comps} == set(defs)
    sent = []
    for c in comps:
        c.message_sender = lambda s, d, m: sent.append((s, d))
    for c in comps:
        c.start()
    cost, assignment = _cost(dcop, comps)
    assert cost == 0.0, assignment
    assert sent == []  # no boundary — nothing may leave the island


@pytest.mark.parametrize("algo", ["maxsum", "amaxsum"])
def test_island_mixed_sim_parity(algo):
    """Half the chain on an island, half as plain host computations,
    run under the deterministic sim loop: the mixed deployment reaches
    the tree optimum exactly like the all-host one, via wire-identical
    messages.  amaxsum shares the island (one more schedule for the
    same fixed point)."""
    from pydcop_tpu.algorithms import maxsum
    from pydcop_tpu.infrastructure.runtime import _run_sim, solve_host

    dcop = _chain_dcop(8)
    module, defs = _graph_and_defs(dcop, algo=algo)
    # island owns v0..v3 and c0..c2 (c3 = boundary factor v3-v4 stays
    # remote, so the island has BOTH boundary kinds: an owned variable
    # hearing a remote factor (v3<-c3) is exercised, and the remote
    # half keeps an owned-factor boundary in the all-host direction)
    island_names = {f"v{i}" for i in range(4)} | {
        f"c{i}" for i in range(3)
    }
    island_defs = [defs[n] for n in sorted(island_names)]
    host_defs = [
        defs[n] for n in sorted(set(defs) - island_names)
    ]
    comps = module.build_island(island_defs, dcop, seed=1)
    comps += [
        module.build_computation(cd, seed=1) for cd in host_defs
    ]
    t0 = time.perf_counter()
    status, delivered, _size = _run_sim(
        comps, timeout=60, max_msgs=100_000, seed=5, t0=t0,
        snapshot=lambda *a: None,
    )
    assert status == "finished", status  # quiescence, not budget
    assert delivered > 0  # real boundary traffic crossed the seam
    cost, assignment = _cost(dcop, comps)
    assert cost == 0.0, (assignment, delivered)

    # all-host reference run on the same problem
    host = solve_host(dcop, algo, mode="sim", seed=5, timeout=60)
    assert host["cost"] == cost == 0.0


def test_island_owned_factor_boundary():
    """Island owns a FACTOR whose scope is split (one owned variable,
    one remote): the shadow-variable path — pinned remote q, r row
    read-back — must still reach the exact tree optimum."""
    from pydcop_tpu.algorithms import maxsum
    from pydcop_tpu.infrastructure.runtime import _run_sim

    dcop = _chain_dcop(6)
    module, defs = _graph_and_defs(dcop)
    # v0,v1,c0,c1: c1 spans v1 (owned) and v2 (remote) -> shadow
    island_names = {"v0", "v1", "c0", "c1"}
    comps = maxsum.build_island(
        [defs[n] for n in sorted(island_names)], dcop, seed=2
    )
    assert any(
        c.name == "c1" for c in comps
    ), "boundary factor proxy missing"
    comps += [
        module.build_computation(defs[n], seed=2)
        for n in sorted(set(defs) - island_names)
    ]
    status, delivered, _ = _run_sim(
        comps, timeout=60, max_msgs=100_000, seed=7,
        t0=time.perf_counter(), snapshot=lambda *a: None,
    )
    assert status == "finished", status
    cost, assignment = _cost(dcop, comps)
    assert cost == 0.0, (assignment, delivered)


def test_island_mixed_domain_sizes():
    """Heterogeneous domains: a remote (shadow) variable whose domain
    is smaller than the island's d_max.  The shadow q pin must carry
    BIG on padded positions — zeros there let the factor
    marginalization pick an invalid padded value (review-found bug:
    mixed run converged to 5.0 instead of 0.0)."""
    from pydcop_tpu.dcop.dcop import DCOP
    from pydcop_tpu.dcop.objects import Domain, Variable
    from pydcop_tpu.dcop.relations import NAryMatrixRelation
    from pydcop_tpu.algorithms import maxsum
    from pydcop_tpu.infrastructure.runtime import _run_sim

    d4 = Domain("d4", "", [0, 1, 2, 3])
    d2 = Domain("d2", "", [0, 1])
    dcop = DCOP("mixed", objective="min")
    vs = [
        Variable("v0", d4), Variable("v1", d2), Variable("v2", d4)
    ]
    for v in vs:
        dcop.add_variable(v)
    # equality penalized where domains overlap: optimum 0 exists
    def eq_table(da, db):
        t = np.zeros((len(da), len(db)))
        for i, a in enumerate(da):
            for j, b in enumerate(db):
                t[i, j] = 5.0 if a == b else 0.0
        return t

    dcop.add_constraint(
        NAryMatrixRelation(
            [vs[0], vs[1]], eq_table([0, 1, 2, 3], [0, 1]), name="c0"
        )
    )
    dcop.add_constraint(
        NAryMatrixRelation(
            [vs[1], vs[2]], eq_table([0, 1], [0, 1, 2, 3]), name="c1"
        )
    )
    module, defs = _graph_and_defs(dcop)
    # island = {v0, c0}: c0's scope spans v1 (remote, |domain|=2 <
    # island d_max=4) -> the shadow pin's padded tail is live
    comps = maxsum.build_island(
        [defs["v0"], defs["c0"]], dcop, seed=0
    )
    comps += [
        module.build_computation(defs[n], seed=0)
        for n in sorted(set(defs) - {"v0", "c0"})
    ]
    status, _, _ = _run_sim(
        comps, timeout=60, max_msgs=100_000, seed=3,
        t0=time.perf_counter(), snapshot=lambda *a: None,
    )
    assert status == "finished"
    cost, assignment = _cost(dcop, comps)
    assert cost == 0.0, assignment


def test_island_max_objective():
    """objective: max flows through the island's sign handling (the
    compiled side negates at compile; hosts negate in-message): a
    2-var 'prefer different' reward chain maximizes to n-1."""
    from pydcop_tpu.algorithms import maxsum
    from pydcop_tpu.dcop.dcop import DCOP
    from pydcop_tpu.dcop.objects import Domain, Variable
    from pydcop_tpu.dcop.relations import NAryMatrixRelation
    from pydcop_tpu.infrastructure.runtime import _run_sim

    d = Domain("colors", "", [0, 1, 2])
    dcop = DCOP("maxchain", objective="max")
    vs = [Variable(f"v{i}", d) for i in range(4)]
    for v in vs:
        dcop.add_variable(v)
    reward = 1.0 - np.eye(3)  # 1 when different
    for i in range(3):
        dcop.add_constraint(
            NAryMatrixRelation([vs[i], vs[i + 1]], reward, name=f"c{i}")
        )
    module, defs = _graph_and_defs(dcop)
    island_names = {"v0", "v1", "c0", "c1"}
    comps = maxsum.build_island(
        [defs[n] for n in sorted(island_names)], dcop, seed=0
    )
    comps += [
        module.build_computation(defs[n], seed=0)
        for n in sorted(set(defs) - island_names)
    ]
    status, _, _ = _run_sim(
        comps, timeout=60, max_msgs=100_000, seed=1,
        t0=time.perf_counter(), snapshot=lambda *a: None,
    )
    assert status == "finished"
    cost, assignment = _cost(dcop, comps)
    assert cost == 3.0, assignment


@pytest.mark.parametrize("mode", ["sim", "thread"])
def test_solve_accel_island_in_process_runtimes(mode):
    """solve(mode='sim'|'thread', accel_agents=[...]): islands in the
    one-process runtimes, through the public embedding seam.  With two
    declared agents the placement is round-robin; a0's half runs as a
    compiled island, a1's as plain computations."""
    from pydcop_tpu.api import solve
    from pydcop_tpu.dcop.objects import AgentDef

    dcop = _chain_dcop(8)
    dcop.add_agents([AgentDef("a0"), AgentDef("a1")])
    r = solve(
        dcop, "maxsum", mode=mode, seed=4, timeout=60,
        accel_agents=["a0"],
    )
    assert r["cost"] == 0.0, r
    assert r["msg_count"] > 0  # boundary traffic crossed the seam

    # validation: an agent with no placed computations fails fast
    with pytest.raises(ValueError, match="accel_agents"):
        solve(
            dcop, "maxsum", mode=mode, accel_agents=["nope"],
            timeout=30,
        )
    # and a no-island algorithm is rejected up front (mgm2 has
    # none: its 5-phase offer/accept protocol has per-neighbor
    # payloads the lockstep skeleton does not model)
    with pytest.raises(ValueError, match="compiled-island"):
        solve(
            dcop, "mgm2", mode=mode, accel_agents=["a0"], timeout=30
        )


def test_solve_distribution_shapes_island_placement(tmp_path):
    """solve(distribution=...) (reference-parity): an explicit
    Distribution object and a `distribute --output` yaml both shape
    which computations the island owns in sim mode."""
    from pydcop_tpu.api import solve
    from pydcop_tpu.distribution import Distribution

    dcop = _chain_dcop(6)
    mapping = {
        "left": ["v0", "v1", "v2", "c0", "c1"],
        "right": ["v3", "v4", "v5", "c2", "c3", "c4"],
    }
    r = solve(
        dcop, "maxsum", mode="sim", seed=1, timeout=60,
        accel_agents=["left"], distribution=Distribution(mapping),
    )
    assert r["cost"] == 0.0, r

    # same placement from a distribute --output yaml file
    import yaml as _yaml

    pfile = tmp_path / "dist.yaml"
    pfile.write_text(_yaml.safe_dump({"distribution": mapping}))
    r2 = solve(
        dcop, "maxsum", mode="sim", seed=1, timeout=60,
        accel_agents=["left"], distribution=str(pfile),
    )
    assert r2["cost"] == 0.0
    assert r2["assignment"] == r["assignment"]

    # a strategy name needs declared agents
    with pytest.raises(ValueError, match="declared agents"):
        solve(
            dcop, "maxsum", mode="sim", distribution="adhoc",
            accel_agents=["left"], timeout=30,
        )

    # stale placements fail loudly, with hostnet-style messages
    incomplete = dict(mapping)
    incomplete["right"] = incomplete["right"][:-1]  # drop c4
    with pytest.raises(ValueError, match="unhosted"):
        solve(
            dcop, "maxsum", mode="thread", timeout=30,
            distribution=Distribution(incomplete),
        )
    stale = {**mapping, "ghost": ["v99"]}
    with pytest.raises(ValueError, match="unknown computation"):
        solve(
            dcop, "maxsum", mode="thread", timeout=30,
            distribution=Distribution(stale),
        )


def test_solve_process_distribution_placement(tmp_path):
    """Process mode with an explicit placement file: agent processes
    take the placement's names, one per placed agent."""
    from pydcop_tpu.api import solve
    from pydcop_tpu.dcop.yamldcop import load_dcop
    import yaml as _yaml

    dcop = load_dcop(_ring_yaml(6))
    mapping = {
        "west": [f"v{i}" for i in range(3)] + [f"c{i}" for i in range(3)],
        "east": [f"v{i}" for i in range(3, 6)]
        + [f"c{i}" for i in range(3, 6)],
    }
    pfile = tmp_path / "dist.yaml"
    pfile.write_text(_yaml.safe_dump({"distribution": mapping}))
    r = solve(
        dcop, "maxsum", mode="process", rounds=400, timeout=120,
        seed=1, distribution=str(pfile),
    )
    assert r["cost"] == 0.0, r
    assert sorted(r["placement"]) == ["east", "west"]

    with pytest.raises(ValueError, match="conflicts with"):
        solve(
            dcop, "maxsum", mode="process", nb_agents=3,
            distribution=str(pfile), timeout=30,
        )
    # a mistyped placement path must fail before any fork, not be
    # silently reinterpreted as a strategy name
    with pytest.raises(ValueError, match="neither an existing"):
        solve(
            dcop, "maxsum", mode="process",
            distribution=str(pfile) + ".nope", timeout=30,
        )


def test_solve_sim_accel_island_deterministic():
    """The sim-mode island flush trigger is the global queued count —
    fully deterministic: two identical runs give identical results."""
    from pydcop_tpu.api import solve
    from pydcop_tpu.dcop.objects import AgentDef

    def run():
        dcop = _chain_dcop(10)
        dcop.add_agents([AgentDef("a0"), AgentDef("a1")])
        return solve(
            dcop, "maxsum", mode="sim", seed=9, timeout=60,
            accel_agents=["a0"],
        )

    r1, r2 = run(), run()
    assert r1["cost"] == r2["cost"] == 0.0
    assert r1["assignment"] == r2["assignment"]
    assert r1["msg_count"] == r2["msg_count"]


@pytest.mark.parametrize("seed", range(4))
def test_island_random_tree_partition_matches_exact(seed):
    """Property fuzz: on a random TREE with random tables, a random
    island partition of the factor graph must reach the exact optimum
    (min-sum is exact on trees), matching DPOP."""
    import random as _random

    from pydcop_tpu.api import solve
    from pydcop_tpu.dcop.dcop import DCOP
    from pydcop_tpu.dcop.objects import Domain, Variable
    from pydcop_tpu.dcop.relations import NAryMatrixRelation
    from pydcop_tpu.distribution import Distribution

    rnd = _random.Random(seed)
    npr = np.random.RandomState(seed)
    n = 12
    d = Domain("d", "", [0, 1, 2])
    dcop = DCOP(f"tree{seed}")
    vs = [Variable(f"v{i}", d) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    for i in range(1, n):
        j = rnd.randrange(i)  # random tree: parent among earlier vars
        dcop.add_constraint(
            NAryMatrixRelation(
                [vs[j], vs[i]],
                npr.uniform(0, 10, (3, 3)).round(2),
                name=f"c{i}",
            )
        )
    opt = solve(dcop, "dpop")["cost"]

    # random partition; each factor follows its child variable
    island_vars = {f"v{i}" for i in range(n) if rnd.random() < 0.5}
    mapping = {"isl": [], "rest": []}
    for i in range(n):
        mapping["isl" if f"v{i}" in island_vars else "rest"].append(
            f"v{i}"
        )
        if i >= 1:
            mapping[
                "isl" if f"v{i}" in island_vars else "rest"
            ].append(f"c{i}")
    if not mapping["isl"] or not mapping["rest"]:
        mapping["isl"], mapping["rest"] = (
            mapping["isl"] + mapping["rest"]
        )[:3], (mapping["isl"] + mapping["rest"])[3:]
    r = solve(
        dcop, "maxsum", mode="sim", seed=seed, timeout=120,
        accel_agents=["isl"], distribution=Distribution(mapping),
    )
    assert r["cost"] == pytest.approx(opt, abs=1e-3), (
        mapping, r["cost"], opt
    )
    assert r["status"] == "finished"


# -- DSA-family islands (_island_dsa.py) --------------------------------


@pytest.mark.parametrize("algo", ["dsa", "adsa", "dsatuto"])
def test_dsa_island_mixed_sim(algo):
    """Half the variables on a compiled DSA island, half as host
    computations, under the deterministic sim loop: the ring still
    colors to 0 and the run quiesces."""
    from pydcop_tpu.api import solve
    from pydcop_tpu.dcop.objects import AgentDef
    from pydcop_tpu.dcop.dcop import DCOP
    from pydcop_tpu.dcop.objects import Domain, Variable
    from pydcop_tpu.dcop.relations import constraint_from_str

    d = Domain("colors", "", [0, 1, 2])
    dcop = DCOP("ring8")
    vs = [Variable(f"v{i}", d) for i in range(8)]
    for v in vs:
        dcop.add_variable(v)
    for i in range(8):
        dcop.add_constraint(
            constraint_from_str(
                f"c{i}", f"1 if v{i} == v{(i + 1) % 8} else 0", vs
            )
        )
    dcop.add_agents([AgentDef("a0"), AgentDef("a1")])
    r = solve(
        dcop, algo, mode="sim", seed=3, timeout=60,
        accel_agents=["a0"],
    )
    assert r["cost"] == 0.0, r
    assert r["status"] == "finished"  # quiescence, not budget
    assert r["msg_count"] > 0


def test_dsa_island_interior_converges_without_boundary_traffic():
    """Review-found stall: with a tiny burst size and one boundary
    variable, interior-only changes used to produce no outbound
    message, so the island never re-burst and quiesced arbitrarily
    far from a local optimum.  The self-tick keeps it running until
    no strictly-improving move remains — a 30-var chain must reach 0
    even at island_rounds=1."""
    from pydcop_tpu.api import solve
    from pydcop_tpu.distribution import Distribution

    dcop = _chain_dcop(30)
    mapping = {
        "big": [f"v{i}" for i in range(28)],
        "small": ["v28", "v29"],
    }
    r = solve(
        dcop, "dsa", {"island_rounds": 1}, mode="sim", seed=6,
        timeout=120, accel_agents=["big"],
        distribution=Distribution(mapping),
    )
    assert r["cost"] == 0.0, r
    assert r["status"] == "finished"


def test_dsa_island_pure():
    """Whole problem on one DSA island: the start burst alone must
    solve it (no boundary traffic exists)."""
    from pydcop_tpu.api import solve
    from pydcop_tpu.dcop.objects import AgentDef

    dcop = _chain_dcop(8)
    dcop.add_agents([AgentDef("a0")])
    r = solve(
        dcop, "dsa", mode="sim", seed=2, timeout=60,
        accel_agents=["a0"],
    )
    assert r["cost"] == 0.0, r
    # nothing may leave the island; delivered messages can only be
    # self-addressed re-fire ticks (one per post-burst change)
    assert r["msg_count"] <= 3, r


def test_dsa_island_thread_mode():
    from pydcop_tpu.api import solve
    from pydcop_tpu.dcop.objects import AgentDef

    dcop = _chain_dcop(10)
    dcop.add_agents([AgentDef("a0"), AgentDef("a1"), AgentDef("a2")])
    r = solve(
        dcop, "dsa", mode="thread", seed=5, timeout=60,
        accel_agents=["a0", "a2"],  # two islands, one plain agent
    )
    assert r["cost"] == 0.0, r


def _ring_yaml(n=8):
    lines = [
        "name: ring",
        "objective: min",
        "domains:",
        "  colors: {values: [0, 1, 2]}",
        "variables:",
    ]
    for i in range(n):
        lines.append(f"  v{i}: {{domain: colors}}")
    lines.append("constraints:")
    for i in range(n):
        j = (i + 1) % n
        lines.append(f"  c{i}:")
        lines.append("    type: intention")
        lines.append(f"    function: 1 if v{i} == v{j} else 0")
    lines.append(f"agents: [{', '.join(f'a{i}' for i in range(n))}]")
    return "\n".join(lines) + "\n"


def test_solve_process_accel_island():
    """solve(mode='process', accel_agents=[...]) — the embedding
    surface of the heterogeneous island deployment: one of two local
    agent processes runs its subgraph as a compiled island."""
    from pydcop_tpu.api import solve
    from pydcop_tpu.dcop.yamldcop import load_dcop

    dcop = load_dcop(_ring_yaml(8))
    r = solve(
        dcop, "maxsum", mode="process", nb_agents=2, rounds=400,
        timeout=120, seed=1, accel_agents=["a0"],
    )
    assert r["cost"] == 0.0, r
    assert len(r["agents"]) == 2

    # validation: unknown island name fails fast, pre-fork
    with pytest.raises(ValueError, match="accel_agents"):
        solve(
            dcop, "maxsum", mode="process", nb_agents=2,
            accel_agents=["nope"], timeout=30,
        )
    # and the batched engine rejects it with a pointer
    with pytest.raises(ValueError, match="accel_agents"):
        solve(dcop, "maxsum", accel_agents=["a0"], rounds=4)


def test_hostnet_accel_island(tmp_path):
    """Cross-process heterogeneous deployment: agent a1 is a compiled
    island (--accel_agents a1), a2 runs plain host computations; the
    ring still solves to optimum over real TCP frames."""
    yaml_file = tmp_path / "ring.yaml"
    yaml_file.write_text(_ring_yaml())

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["PYDCOP_TPU_PLATFORM"] = "cpu"

    port = 9440 + (os.getpid() % 120)
    orch = subprocess.Popen(
        [
            sys.executable, "-m", "pydcop_tpu", "orchestrator",
            str(yaml_file), "-a", "maxsum", "--runtime", "host",
            "--port", str(port), "--nb_agents", "2",
            "--rounds", "400", "--seed", "3",
            "--accel_agents", "a1",
        ],
        env=env, cwd=str(tmp_path),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    time.sleep(0.5)
    agents = [
        subprocess.Popen(
            [
                sys.executable, "-m", "pydcop_tpu", "agent",
                "--names", name, "--runtime", "host",
                "--orchestrator", f"localhost:{port}",
            ],
            env=env, cwd=str(tmp_path),
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for name in ("a1", "a2")
    ]
    try:
        orc_out, orc_err = orch.communicate(timeout=180)
        assert orch.returncode == 0, orc_err[-3000:]
        start = orc_out.index("{")
        result = json.loads(orc_out[start:])
        assert result["cost"] == 0.0, result
        assert set(result["assignment"]) == {
            f"v{i}" for i in range(8)
        }
        # the island agent really hosted computations
        assert result["placement"]["a1"], result["placement"]
        assert result["msg_count"] > 0
    finally:
        for a in agents:
            if a.poll() is None:
                a.kill()
            a.communicate(timeout=30)
        if orch.poll() is None:
            orch.kill()
            orch.communicate(timeout=30)


def test_mgm_island_pure():
    """Whole chain on one lockstep MGM island: the interior-convergence
    path alone must reach the tree's proper coloring (every 1-opt
    fixed point of a chain with 3 colors is conflict-free), with zero
    wire messages."""
    from pydcop_tpu.algorithms import mgm

    dcop = _chain_dcop(8)
    module, defs = _graph_and_defs(dcop, algo="mgm")
    comps = mgm.build_island(list(defs.values()), dcop, seed=1)
    assert {c.name for c in comps} == set(defs)
    sent = []
    for c in comps:
        c.message_sender = lambda s, d, m: sent.append((s, d))
    for c in comps:
        c.start()
    cost, assignment = _cost(dcop, comps)
    assert cost == 0.0, assignment
    assert sent == []  # no boundary — nothing may leave the island


def test_mgm_island_lockstep_exact_parity():
    """Half the chain on a lockstep MGM island, half as plain host
    computations: MGM with the lexic tie-break is DETERMINISTIC, so
    the mixed deployment must replay the all-host run exactly — the
    same per-variable value histories, the same final assignment —
    while the interior value/gain messages become array ops (the
    lockstep trade: fewer wire messages, never more rounds per
    round)."""
    from pydcop_tpu.algorithms import mgm
    from pydcop_tpu.infrastructure.computations import (
        VariableComputation,
    )
    from pydcop_tpu.infrastructure.runtime import _run_sim

    dcop = _chain_dcop(10)
    module, defs = _graph_and_defs(dcop, algo="mgm")
    island_names = {f"v{i}" for i in range(5)}

    comps_mixed = mgm.build_island(
        [defs[n] for n in sorted(island_names)], dcop, seed=3
    )
    comps_mixed += [
        module.build_computation(defs[n], seed=3)
        for n in sorted(set(defs) - island_names)
    ]
    status, delivered_mixed, _ = _run_sim(
        comps_mixed, timeout=60, max_msgs=4_000, seed=5,
        t0=time.perf_counter(), snapshot=lambda *a: None,
    )
    cost_mixed, asg_mixed = _cost(dcop, comps_mixed)
    hist_mixed = {
        c.name: list(c.value_history)
        for c in comps_mixed
        if isinstance(c, VariableComputation)
    }

    comps_host = [
        module.build_computation(defs[n], seed=3) for n in sorted(defs)
    ]
    status_h, delivered_host, _ = _run_sim(
        comps_host, timeout=60, max_msgs=8_000, seed=5,
        t0=time.perf_counter(), snapshot=lambda *a: None,
    )
    cost_host, asg_host = _cost(dcop, comps_host)
    hist_host = {
        c.name: list(c.value_history) for c in comps_host
    }

    assert cost_mixed == cost_host == 0.0, (asg_mixed, asg_host)
    assert asg_mixed == asg_host
    # bit-exact trajectory: every variable changed through the same
    # value sequence in both deployments
    assert hist_mixed == hist_host
    assert delivered_mixed > 0  # real boundary traffic crossed


def test_dba_island_lockstep_exact_parity():
    """Lockstep DBA island vs all-host: DBA with the name tie-break is
    deterministic, so the mixed deployment must replay the all-host
    run exactly — same per-variable value histories, same final
    assignment — including the breakout flags crossing the island
    seam so endpoint weight copies stay equal."""
    from pydcop_tpu.algorithms import dba
    from pydcop_tpu.infrastructure.computations import (
        VariableComputation,
    )
    from pydcop_tpu.infrastructure.runtime import _run_sim

    dcop = _chain_dcop(10)
    module, defs = _graph_and_defs(dcop, algo="dba")
    island_names = {f"v{i}" for i in range(5)}

    comps_mixed = dba.build_island(
        [defs[n] for n in sorted(island_names)], dcop, seed=3
    )
    comps_mixed += [
        module.build_computation(defs[n], seed=3)
        for n in sorted(set(defs) - island_names)
    ]
    status, delivered_mixed, _ = _run_sim(
        comps_mixed, timeout=60, max_msgs=4_000, seed=5,
        t0=time.perf_counter(), snapshot=lambda *a: None,
    )
    cost_mixed, asg_mixed = _cost(dcop, comps_mixed)
    hist_mixed = {
        c.name: list(c.value_history)
        for c in comps_mixed
        if isinstance(c, VariableComputation)
    }

    comps_host = [
        module.build_computation(defs[n], seed=3) for n in sorted(defs)
    ]
    _run_sim(
        comps_host, timeout=60, max_msgs=8_000, seed=5,
        t0=time.perf_counter(), snapshot=lambda *a: None,
    )
    cost_host, asg_host = _cost(dcop, comps_host)
    hist_host = {c.name: list(c.value_history) for c in comps_host}

    assert cost_mixed == cost_host == 0.0, (asg_mixed, asg_host)
    assert asg_mixed == asg_host
    assert hist_mixed == hist_host
    assert delivered_mixed > 0


def test_dba_island_breaks_out_of_local_minimum():
    """The breakout machinery must survive islanding: an instance MGM
    stays stuck on (cost > 1 at its 1-opt fixed point) is solved to
    conflict-free by the DBA island + host mix — the weight increases
    crossing the seam are what make it possible."""
    import __graft_entry__ as g
    from pydcop_tpu.algorithms import dba
    from pydcop_tpu.infrastructure import solve_host
    from pydcop_tpu.infrastructure.runtime import _run_sim

    dcop = g._make_coloring_dcop(24, degree=2, seed=3)
    r_mgm = solve_host(dcop, "mgm", {}, mode="sim", rounds=400, timeout=30)
    assert r_mgm["cost"] > 1.0  # the stuck instance

    module, defs = _graph_and_defs(dcop, algo="dba")
    island_names = {f"v{i}" for i in range(0, 24, 2)}  # alternating:
    # every second variable islanded -> many boundary constraints
    comps = dba.build_island(
        [defs[n] for n in sorted(island_names)], dcop, seed=0
    )
    comps += [
        module.build_computation(defs[n], seed=0)
        for n in sorted(set(defs) - island_names)
    ]
    _run_sim(
        comps, timeout=60, max_msgs=40_000, seed=0,
        t0=time.perf_counter(), snapshot=lambda *a: None,
    )
    cost, assignment = _cost(dcop, comps)
    assert cost < 0.5, (cost, assignment)  # broke out: conflict-free


@pytest.mark.parametrize("imode", ["E", "R", "C", "T"])
def test_gdba_island_lockstep_exact_parity(imode):
    """Lockstep GDBA island vs all-host, across all four increase
    modes: GDBA with the name tie-break is deterministic, so the
    mixed deployment must replay the all-host run exactly — the
    per-CELL weight flags crossing the seam as (constraint, cells)
    label lists keep endpoint weight-matrix copies equal."""
    from pydcop_tpu.algorithms import gdba
    from pydcop_tpu.infrastructure.computations import (
        VariableComputation,
    )
    from pydcop_tpu.infrastructure.runtime import _run_sim

    dcop = _chain_dcop(10)
    module, defs = _graph_and_defs(
        dcop, params={"increase_mode": imode}, algo="gdba"
    )
    island_names = {f"v{i}" for i in range(5)}

    comps_mixed = gdba.build_island(
        [defs[n] for n in sorted(island_names)], dcop, seed=3
    )
    comps_mixed += [
        module.build_computation(defs[n], seed=3)
        for n in sorted(set(defs) - island_names)
    ]
    status, delivered_mixed, _ = _run_sim(
        comps_mixed, timeout=60, max_msgs=4_000, seed=5,
        t0=time.perf_counter(), snapshot=lambda *a: None,
    )
    cost_mixed, asg_mixed = _cost(dcop, comps_mixed)
    hist_mixed = {
        c.name: list(c.value_history)
        for c in comps_mixed
        if isinstance(c, VariableComputation)
    }

    comps_host = [
        module.build_computation(defs[n], seed=3) for n in sorted(defs)
    ]
    _run_sim(
        comps_host, timeout=60, max_msgs=8_000, seed=5,
        t0=time.perf_counter(), snapshot=lambda *a: None,
    )
    cost_host, asg_host = _cost(dcop, comps_host)
    hist_host = {c.name: list(c.value_history) for c in comps_host}

    assert cost_mixed == cost_host == 0.0, (asg_mixed, asg_host)
    assert asg_mixed == asg_host
    assert hist_mixed == hist_host
    assert delivered_mixed > 0


def test_gdba_island_breaks_out_of_local_minimum():
    """The per-cell breakout machinery survives islanding: the
    MGM-stuck instance is solved conflict-free by the GDBA island +
    host mix (cell-targeted weight increases crossing the seam)."""
    import __graft_entry__ as g
    from pydcop_tpu.algorithms import gdba
    from pydcop_tpu.infrastructure import solve_host
    from pydcop_tpu.infrastructure.runtime import _run_sim

    dcop = g._make_coloring_dcop(24, degree=2, seed=3)
    r_mgm = solve_host(dcop, "mgm", {}, mode="sim", rounds=400, timeout=30)
    assert r_mgm["cost"] > 1.0  # the stuck instance

    module, defs = _graph_and_defs(
        dcop, params={"increase_mode": "R"}, algo="gdba"
    )
    island_names = {f"v{i}" for i in range(0, 24, 2)}
    comps = gdba.build_island(
        [defs[n] for n in sorted(island_names)], dcop, seed=0
    )
    comps += [
        module.build_computation(defs[n], seed=0)
        for n in sorted(set(defs) - island_names)
    ]
    _run_sim(
        comps, timeout=60, max_msgs=40_000, seed=0,
        t0=time.perf_counter(), snapshot=lambda *a: None,
    )
    cost, assignment = _cost(dcop, comps)
    assert cost < 0.5, (cost, assignment)  # broke out: conflict-free


@pytest.mark.parametrize("algo", ["gdba", "dba"])
def test_lockstep_island_parity_multi_neighbor_boundary(algo):
    """Exact parity on a RING with ALTERNATING island placement: every
    remote variable then borders TWO island variables, so its
    broadcast payload reaches the island through two proxies — the
    island must apply each sender's flags/gains ONCE (review-found
    GDBA bug: per-(proxy, sender) application double-counted the
    per-cell weight increases on exactly this topology, which the
    chain parity tests could not see)."""
    from pydcop_tpu.algorithms import load_algorithm_module
    from pydcop_tpu.dcop.dcop import DCOP
    from pydcop_tpu.dcop.objects import Domain, Variable
    from pydcop_tpu.dcop.relations import NAryMatrixRelation
    from pydcop_tpu.infrastructure.computations import (
        VariableComputation,
    )
    from pydcop_tpu.infrastructure.runtime import _run_sim

    # a FRUSTRATED odd ring (2 colors, unsatisfiable): quasi-local
    # minima are guaranteed, so breakout flags actually FLOW across
    # the seam — on a satisfiable ring the flag path never fires and
    # the double-count bug is invisible.  With the even vars islanded,
    # remote v1 borders island vars v0 AND v2 (the two-proxy case).
    n = 9
    d2 = Domain("colors", "", [0, 1])
    dcop = DCOP("cycle", objective="min")
    vs = [Variable(f"v{i}", d2) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    eye = np.eye(2)
    for i in range(n):
        dcop.add_constraint(
            NAryMatrixRelation(
                [vs[i], vs[(i + 1) % n]], eye, name=f"c{i}"
            )
        )
    island_names = {f"v{i}" for i in range(0, n - 1, 2)}
    mod = load_algorithm_module(algo)
    module, defs = _graph_and_defs(
        dcop,
        params={"increase_mode": "R"} if algo == "gdba" else None,
        algo=algo,
    )

    comps_mixed = mod.build_island(
        [defs[nm] for nm in sorted(island_names)], dcop, seed=4
    )
    comps_mixed += [
        module.build_computation(defs[nm], seed=4)
        for nm in sorted(set(defs) - island_names)
    ]
    _run_sim(
        comps_mixed, timeout=60, max_msgs=6_000, seed=9,
        t0=time.perf_counter(), snapshot=lambda *a: None,
    )
    cost_mixed, asg_mixed = _cost(dcop, comps_mixed)
    hist_mixed = {
        c.name: list(c.value_history)
        for c in comps_mixed
        if isinstance(c, VariableComputation)
    }

    comps_host = [
        module.build_computation(defs[nm], seed=4)
        for nm in sorted(defs)
    ]
    _run_sim(
        comps_host, timeout=60, max_msgs=12_000, seed=9,
        t0=time.perf_counter(), snapshot=lambda *a: None,
    )
    cost_host, asg_host = _cost(dcop, comps_host)
    hist_host = {c.name: list(c.value_history) for c in comps_host}

    # the instance is unsatisfiable (odd cycle, 2 colors), so both
    # deployments oscillate under breakout forever and the message
    # budgets cut them at different ROUND counts: parity is per-var
    # trajectory-PREFIX equality (a weight divergence would break the
    # oscillation alignment within a few rounds of the first flag)
    for v in hist_host:
        m, h = hist_mixed[v], hist_host[v]
        k = min(len(m), len(h))
        assert k >= 6, (v, m, h)  # deep enough to cover the flag era
        assert m[:k] == h[:k], (v, m, h)


def test_gdba_island_applies_each_senders_flags_once():
    """A remote bordering TWO island variables delivers its broadcast
    (value, flags) payload through BOTH proxies; the island must apply
    the sender's per-cell weight increases ONCE, as every host
    endpoint does (review-found double-count — invisible to the
    symmetric e2e parity runs, pinned here at the unit level)."""
    from pydcop_tpu.algorithms import _island_gdba
    from pydcop_tpu.dcop.dcop import DCOP
    from pydcop_tpu.dcop.objects import Domain, Variable
    from pydcop_tpu.dcop.relations import NAryMatrixRelation
    from pydcop_tpu.algorithms import (
        AlgorithmDef,
        ComputationDef,
        load_algorithm_module,
        prepare_algo_params,
    )
    from pydcop_tpu.graphs import load_graph_module

    # path v0 - u - v2 plus v0 - v2: island owns v0, v2; remote u
    # borders both islanded variables
    d2 = Domain("colors", "", [0, 1])
    dcop = DCOP("tri", objective="min")
    v0, u, v2 = (Variable(nm, d2) for nm in ("v0", "u", "v2"))
    for v in (v0, u, v2):
        dcop.add_variable(v)
    eye = np.eye(2)
    dcop.add_constraint(NAryMatrixRelation([v0, u], eye, name="c0"))
    dcop.add_constraint(NAryMatrixRelation([u, v2], eye, name="c1"))
    dcop.add_constraint(NAryMatrixRelation([v0, v2], eye, name="c2"))

    module = load_algorithm_module("gdba")
    params = prepare_algo_params({}, module.algo_params)
    graph = load_graph_module(module.GRAPH_TYPE).build_computation_graph(
        dcop
    )
    algo_def = AlgorithmDef("gdba", params, dcop.objective)
    defs = {n.name: ComputationDef(n, algo_def) for n in graph.nodes}
    comps = _island_gdba.build_island(
        [defs["v0"], defs["v2"]], dcop, seed=1
    )
    island = comps[0]._island
    sent = []
    for c in comps:
        c.message_sender = lambda s, d, m: sent.append((s, d))
    for c in comps:
        c.start()

    # u's broadcast payload arrives through BOTH proxies
    k, row, _ = island._con_meta["c0"]
    before = island._weights[k][row].copy()
    got = {
        ("v0", "u"): (0, [("c0", [(0, 0)])]),
        ("v2", "u"): (0, [("c0", [(0, 0)])]),
    }
    island._pin_values(got)
    island.phase0_complete(got)
    after = island._weights[k][row]
    # cell (0, 0) of c0 increased by EXACTLY 1.0 — not once per proxy
    assert after[0] - before[0] == 1.0, (before, after)
