"""CLI end-to-end tests: spawn the real CLI as a subprocess and parse
its JSON output (the reference's ``tests/dcop_cli`` strategy)."""

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
INSTANCES = Path(__file__).resolve().parent / "instances"

CLI_ENV = {
    **os.environ,
    # keep any pre-existing entries (e.g. the TPU plugin's site dir)
    "PYTHONPATH": str(REPO) + os.pathsep + os.environ.get("PYTHONPATH", ""),
    # CLI tests run on CPU: pin through the conftest-documented override
    "PYDCOP_TPU_PLATFORM": "cpu",
}


def run_cli(*args, timeout=120):
    return subprocess.run(
        [sys.executable, "-m", "pydcop_tpu", *args],
        capture_output=True,
        text=True,
        timeout=timeout,
        env=CLI_ENV,
        cwd=str(REPO),
    )


@pytest.fixture(scope="module")
def ring_yaml(tmp_path_factory):
    p = tmp_path_factory.mktemp("instances") / "ring6.yaml"
    lines = [
        "name: ring6",
        "objective: min",
        "domains:",
        "  colors: {values: [R, G, B]}",
        "variables:",
    ]
    for i in range(6):
        lines.append(f"  v{i}: {{domain: colors}}")
    lines.append("constraints:")
    for i in range(6):
        j = (i + 1) % 6
        lines.append(f"  c{i}:")
        lines.append("    type: intention")
        lines.append(f"    function: 1 if v{i} == v{j} else 0")
    lines.append("agents: [a0, a1, a2, a3, a4, a5]")
    p.write_text("\n".join(lines) + "\n")
    return str(p)


def test_solve_command(ring_yaml):
    r = run_cli(
        "solve", "--algo", "dsa", "--rounds", "100",
        "--seed", "2", ring_yaml,
    )
    assert r.returncode == 0, r.stderr
    result = json.loads(r.stdout)
    assert result["cost"] == 0.0
    assert result["status"] == "finished"
    assert set(result["assignment"]) == {f"v{i}" for i in range(6)}


def test_solve_algo_params_and_output(ring_yaml, tmp_path):
    out = tmp_path / "result.json"
    metrics = tmp_path / "run.csv"
    r = run_cli(
        "solve", "--algo", "dsa",
        "-p", "variant:A", "-p", "probability:0.9",
        "--rounds", "50", "--output", str(out),
        "--run_metrics", str(metrics),
        ring_yaml,
    )
    assert r.returncode == 0, r.stderr
    saved = json.loads(out.read_text())
    assert saved["cycle"] == 50
    lines = metrics.read_text().strip().splitlines()
    assert lines[0] == "time,cycle,cost,msg_count"
    assert len(lines) == 51


def test_solve_bad_param(ring_yaml):
    r = run_cli("solve", "--algo", "dsa", "-p", "variant:Z", ring_yaml)
    assert r.returncode != 0
    assert "variant" in r.stderr


def test_solve_sim_accel_agents(ring_yaml):
    """--accel_agents in the one-process sim runtime: a0's placed
    subgraph runs as a compiled island, the rest as host code."""
    r = run_cli(
        "solve", "--algo", "maxsum", "-m", "sim", "--rounds", "400",
        "--accel_agents", "a0", "--seed", "2", ring_yaml,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    result = json.loads(r.stdout[r.stdout.index("{"):])
    assert result["cost"] == 0.0, result
    assert result["msg_count"] > 0


def test_graph_command(ring_yaml):
    r = run_cli("graph", "--algo", "dsa", ring_yaml)
    assert r.returncode == 0, r.stderr
    result = json.loads(r.stdout)
    assert result["graph"] == "constraints_hypergraph"
    assert result["nodes"] == 6
    assert result["links"] == 6


def test_solve_multiple_files(ring_yaml, tmp_path):
    # agents in a separate file, merged with the problem file
    extra = tmp_path / "extra_agents.yaml"
    extra.write_text("agents: [b1, b2]\n")
    r = run_cli(
        "solve", "--algo", "dsa", "--rounds", "30", ring_yaml, str(extra)
    )
    assert r.returncode == 0, r.stderr
    result = json.loads(r.stdout)
    assert result["status"] == "finished"


def test_solve_many_files(ring_yaml, tmp_path):
    """--many: each file is its own instance; the output is a JSON
    array of per-instance results, same-bucket files batched."""
    # a second, slightly smaller ring — same pow2:16 bucket
    other = tmp_path / "ring5.yaml"
    lines = [
        "name: ring5",
        "objective: min",
        "domains:",
        "  colors: {values: [R, G, B]}",
        "variables:",
    ]
    for i in range(5):
        lines.append(f"  v{i}: {{domain: colors}}")
    lines.append("constraints:")
    for i in range(5):
        j = (i + 1) % 5
        lines.append(f"  c{i}:")
        lines.append("    type: intention")
        lines.append(f"    function: 1 if v{i} == v{j} else 0")
    lines.append("agents: [a0, a1, a2, a3, a4]")
    other.write_text("\n".join(lines) + "\n")
    r = run_cli(
        "solve", "--many", "--algo", "mgm", "--rounds", "24",
        "--seed", "2", "--pad_policy", "pow2:16",
        ring_yaml, str(other),
    )
    assert r.returncode == 0, r.stderr
    results = json.loads(r.stdout)
    assert isinstance(results, list) and len(results) == 2
    assert [res["instances_batched"] for res in results] == [2, 2]
    assert set(results[0]["assignment"]) == {f"v{i}" for i in range(6)}
    assert set(results[1]["assignment"]) == {f"v{i}" for i in range(5)}
    assert all(res["status"] == "finished" for res in results)


def test_solve_many_rejects_single_run_options(ring_yaml):
    r = run_cli(
        "solve", "--many", "--algo", "mgm", "--uiport", "18123",
        ring_yaml,
    )
    assert r.returncode != 0
    assert "--uiport" in r.stderr


def test_run_command_with_scenario(ring_yaml, tmp_path):
    scenario = tmp_path / "scenario.yaml"
    scenario.write_text(
        "events:\n"
        "  - id: e1\n"
        "    actions:\n"
        "      - type: remove_agent\n"
        "        agent: a0\n"
        "  - delay: 0.2\n"
    )
    r = run_cli(
        "run", ring_yaml, "-a", "dsa", "-s", str(scenario),
        "-k", "1", "--final_rounds", "30",
    )
    assert r.returncode == 0, r.stderr
    result = json.loads(r.stdout)
    assert result["lost_computations"] == []
    assert "a0" not in result["agents_final"]
    assert any(
        e.get("action") == "remove_agent" for e in result["events"]
    )


def test_replica_dist_command(ring_yaml):
    r = run_cli("replica_dist", ring_yaml, "-k", "2", "-a", "dsa")
    assert r.returncode == 0, r.stderr
    result = json.loads(r.stdout)
    assert result["ktarget"] == 2
    for comp, reps in result["replica_distribution"].items():
        assert len(reps) == 2


def test_solve_metrics_value_change_and_period(ring_yaml, tmp_path):
    import csv as csvmod

    vc = tmp_path / "vc.csv"
    r = run_cli(
        "solve", "--algo", "dsa", "--rounds", "60", ring_yaml,
        "--collect_on", "value_change", "--run_metrics", str(vc),
    )
    assert r.returncode == 0, r.stderr
    with open(vc, newline="") as f:
        rows = list(csvmod.DictReader(f))
    # only improvement/deterioration rounds are logged
    assert 0 < len(rows) < 60
    costs = [row["cost"] for row in rows]
    assert all(costs[i] != costs[i + 1] for i in range(len(costs) - 1))

    per = tmp_path / "per.csv"
    r = run_cli(
        "solve", "--algo", "dsa", "--rounds", "60", ring_yaml,
        "--collect_on", "period", "--period", "0.001",
        "--run_metrics", str(per),
    )
    assert r.returncode == 0, r.stderr
    with open(per, newline="") as f:
        rows = list(csvmod.DictReader(f))
    assert rows, "period sampling produced no rows"
    times = [float(row["time"]) for row in rows]
    assert times == sorted(times)


def test_solve_metrics_host_modes(ring_yaml, tmp_path):
    """The host runtimes feed the same anytime-metrics CSV surface as
    the batched engine (review-found gap: they used to emit only the
    header)."""
    import csv as csvmod

    vc = tmp_path / "sim_vc.csv"
    r = run_cli(
        "solve", "--algo", "maxsum", "-m", "sim", "--rounds", "200",
        ring_yaml, "--collect_on", "value_change",
        "--run_metrics", str(vc),
    )
    assert r.returncode == 0, r.stderr
    with open(vc, newline="") as f:
        rows = list(csvmod.DictReader(f))
    assert rows, "sim mode produced no anytime rows"
    assert all(row["cost"] != "" for row in rows)
