"""Cold-start budget: ``import pydcop_tpu`` (and the embedding/CLI
surfaces) must stay light.

BENCH_r05 lost its entire ``init`` stage (2 x 90s) "stuck in imports":
on the TPU image, pulling jax costs tens of seconds, and the package
used to pull it eagerly through ``pydcop_tpu.ops``.  The import chain
is now lazy — ``pydcop_tpu``, ``pydcop_tpu.api`` and the CLI parser
import without jax (it loads on first compile/solve) — and these
tests pin that property plus a generous wall-clock budget so a stray
module-level import fails CI instead of the next bench round.

Budgets are wall-clock in a fresh subprocess.  Recorded on this CPU
image: ``import pydcop_tpu`` ~0.2s, ``import pydcop_tpu.api`` ~0.35s
(both jax-free).  The budget is ~10x the recording — it exists to
catch "somebody re-imported jax at module level" (an order-of-
magnitude regression), not scheduler noise.
"""

import subprocess
import sys

import pytest

# ~10x the recorded cold-start on this image; a jax pull blows well
# past this on any hardware this repo targets
IMPORT_BUDGET_SECONDS = 3.0


def _run(code: str) -> str:
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    return out.stdout.strip()


def test_package_import_within_budget_and_jax_free():
    dt = float(
        _run(
            "import sys, time; t0 = time.perf_counter(); "
            "import pydcop_tpu; "
            "assert 'jax' not in sys.modules, 'package pulls jax'; "
            "assert 'numpy' not in sys.modules, 'package pulls numpy'; "
            "print(time.perf_counter() - t0)"
        )
    )
    assert dt < IMPORT_BUDGET_SECONDS, (
        f"import pydcop_tpu took {dt:.2f}s (budget "
        f"{IMPORT_BUDGET_SECONDS}s) — a heavy module-level import "
        "crept back in; see -X importtime"
    )


def test_api_import_defers_jax():
    """The embedding surface (api.solve & co) compiles lazily — the
    jax import must not run until a problem is actually compiled."""
    _run(
        "import sys; import pydcop_tpu.api; "
        "assert 'jax' not in sys.modules, "
        "'pydcop_tpu.api pulls jax at import time'"
    )


def test_cli_parser_defers_jax():
    """``pydcop_tpu --help`` class startup: building the full parser
    (which imports every commands/ module) must stay jax-free."""
    _run(
        "import sys; from pydcop_tpu.cli import build_parser; "
        "build_parser(); "
        "assert 'jax' not in sys.modules, "
        "'a commands/ module pulls jax at import time'"
    )


def test_lint_cli_is_jax_free():
    """``pydcop_tpu lint`` parses, scans the whole package and diffs
    the baseline WITHOUT importing jax: graftlint is stdlib-``ast``
    only, so linting the jax-free surface cannot itself violate it.
    (Also re-proves end-to-end that the repo lints clean: rc == 0.)"""
    _run(
        "import sys; from pydcop_tpu.cli import main; "
        "rc = main(['lint', '--json']); "
        "assert rc == 0, f'lint found new violations (rc={rc})'; "
        "assert 'jax' not in sys.modules, "
        "'the lint CLI path pulls jax'"
    )


def test_ops_padding_is_jax_free():
    """The host-path DPOP engines import ops.padding (level-pack
    keys) at module level — it must never grow a jax dependency."""
    _run(
        "import sys; from pydcop_tpu.ops.padding import "
        "util_level_key, pad_util_parts, as_pad_policy; "
        "assert 'jax' not in sys.modules"
    )


def test_ops_lazy_reexports_still_resolve():
    """PEP 562 laziness must not break the public ``pydcop_tpu.ops``
    surface: every advertised symbol resolves (pulling jax is fine
    HERE — this is the moment it's supposed to load)."""
    import pydcop_tpu.ops as ops

    for name in ops.__all__:
        assert getattr(ops, name) is not None, name
    with pytest.raises(AttributeError):
        ops.definitely_not_a_symbol
