"""Tests for the computation-graph builders (L2)."""

import pytest

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import Domain, Variable
from pydcop_tpu.dcop.relations import constraint_from_str
from pydcop_tpu.graphs import (
    list_available_graph_models,
    load_graph_module,
)
from pydcop_tpu.graphs import (
    constraints_hypergraph,
    factor_graph,
    ordered_graph,
    pseudotree,
)

D = Domain("d", "", [0, 1, 2])


def ring_dcop(n=4):
    """Ring of n variables: v0-v1-...-v(n-1)-v0."""
    dcop = DCOP("ring")
    vs = [Variable(f"v{i}", D) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    for i in range(n):
        j = (i + 1) % n
        dcop.add_constraint(
            constraint_from_str(
                f"c{i}_{j}", f"1 if v{i} == v{j} else 0", vs
            )
        )
    return dcop


def test_load_graph_module():
    assert set(list_available_graph_models()) == {
        "constraints_hypergraph",
        "factor_graph",
        "pseudotree",
        "ordered_graph",
    }
    mod = load_graph_module("factor_graph")
    assert hasattr(mod, "build_computation_graph")
    with pytest.raises(ValueError):
        load_graph_module("nope")


def test_constraints_hypergraph():
    dcop = ring_dcop(4)
    g = constraints_hypergraph.build_computation_graph(dcop)
    assert len(g.nodes) == 4
    n0 = g.node("v0")
    assert set(n0.neighbors) == {"v1", "v3"}
    assert {c.name for c in n0.constraints} == {"c0_1", "c3_0"}
    assert len(g.links) == 4


def test_hypergraph_ternary_constraint():
    dcop = DCOP("t")
    vs = [Variable(f"v{i}", D) for i in range(3)]
    for v in vs:
        dcop.add_variable(v)
    dcop.add_constraint(constraint_from_str("c", "v0 + v1 + v2", vs))
    g = constraints_hypergraph.build_computation_graph(dcop)
    assert set(g.node("v0").neighbors) == {"v1", "v2"}
    assert len(g.links) == 1
    assert set(g.links[0].nodes) == {"v0", "v1", "v2"}


def test_factor_graph():
    dcop = ring_dcop(3)
    g = factor_graph.build_computation_graph(dcop)
    # 3 variable nodes + 3 factor nodes
    assert len(g.nodes) == 6
    var_nodes = [n for n in g.nodes if n.type == "VariableComputationNode"]
    factor_nodes = [n for n in g.nodes if n.type == "FactorComputationNode"]
    assert len(var_nodes) == 3 and len(factor_nodes) == 3
    f = g.node("c0_1")
    assert set(f.neighbors) == {"v0", "v1"}
    v = g.node("v0")
    assert set(v.neighbors) == {"c0_1", "c2_0"}
    # edges = sum of arities
    assert len(g.links) == 6


def test_pseudotree_ring():
    dcop = ring_dcop(4)
    g = pseudotree.build_computation_graph(dcop)
    assert len(g.roots) == 1
    root = g.roots[0]
    assert g.node(root).is_root
    # every non-root has a parent; tree has n-1 tree edges + 1 back edge
    tree_edges = [l for l in g.links if l.type == "tree"]
    back_edges = [l for l in g.links if l.type == "back"]
    assert len(tree_edges) == 3
    assert len(back_edges) == 1
    # pseudo relation is symmetric
    for l in back_edges:
        src, tgt = l.source, l.target
        assert tgt in g.node(src).pseudo_parents
        assert src in g.node(tgt).pseudo_children


def test_pseudotree_branch_property():
    """Every constraint's scope must lie on one root-to-leaf branch."""
    import itertools
    import random

    rnd = random.Random(0)
    dcop = DCOP("rand")
    vs = [Variable(f"v{i}", D) for i in range(12)]
    for v in vs:
        dcop.add_variable(v)
    pairs = rnd.sample(list(itertools.combinations(range(12), 2)), 18)
    for a, b in pairs:
        dcop.add_constraint(
            constraint_from_str(f"c{a}_{b}", f"v{a} * v{b}", vs)
        )
    g = pseudotree.build_computation_graph(dcop)

    def ancestors(name):
        out = set()
        n = g.node(name)
        while n.parent is not None:
            out.add(n.parent)
            n = g.node(n.parent)
        return out

    for c in dcop.constraints.values():
        for a, b in itertools.combinations(c.scope_names, 2):
            assert (
                a in ancestors(b) or b in ancestors(a)
            ), f"constraint {c.name}: {a} and {b} not on one branch"


def test_pseudotree_explicit_root_and_forest():
    dcop = ring_dcop(3)
    # add a disconnected variable pair
    x, y = Variable("x", D), Variable("y", D)
    dcop.add_variable(x)
    dcop.add_variable(y)
    dcop.add_constraint(constraint_from_str("cxy", "x + y", [x, y]))
    g = pseudotree.build_computation_graph(dcop, root="v1")
    assert g.roots[0] == "v1"
    assert len(g.roots) == 2  # forest: ring component + xy component
    # separator of a ring leaf contains parent (+ pseudo-parent)
    for name in ("v0", "v2"):
        n = g.node(name)
        if n.is_leaf:
            assert len(g.separator(name)) == 2


def test_pseudotree_dfs_order():
    dcop = ring_dcop(4)
    g = pseudotree.build_computation_graph(dcop)
    order = g.depth_first_order(g.roots[0])
    assert len(order) == 4
    assert order[0] == g.roots[0]
    # parents always appear before children
    pos = {n: i for i, n in enumerate(order)}
    for n in order:
        p = g.node(n).parent
        if p is not None:
            assert pos[p] < pos[n]


def test_ordered_graph():
    dcop = ring_dcop(3)
    g = ordered_graph.build_computation_graph(dcop)
    assert g.ordering == ["v0", "v1", "v2"]
    assert g.next_node("v0") == "v1"
    assert g.next_node("v2") is None
    assert g.previous_node("v0") is None
    n1 = g.node("v1")
    assert n1.position == 1
    assert set(n1.neighbors) == {"v0", "v2"}


def test_ordered_graph_custom_ordering():
    dcop = ring_dcop(3)
    g = ordered_graph.build_computation_graph(
        dcop, ordering=["v2", "v0", "v1"]
    )
    assert g.ordering == ["v2", "v0", "v1"]
    with pytest.raises(ValueError):
        ordered_graph.build_computation_graph(dcop, ordering=["v0"])


def test_density():
    dcop = ring_dcop(4)
    g = constraints_hypergraph.build_computation_graph(dcop)
    assert g.density() == pytest.approx(2 * 4 / (4 * 3))
