"""Tests for run-state checkpoint/resume (engine.checkpoint)."""

import jax
import numpy as np
import pytest

from pydcop_tpu.algorithms import load_algorithm_module, prepare_algo_params
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import Domain, Variable
from pydcop_tpu.dcop.relations import constraint_from_str
from pydcop_tpu.engine.batched import run_batched
from pydcop_tpu.engine.checkpoint import load_checkpoint, save_checkpoint
from pydcop_tpu.ops.compile import compile_dcop

D = Domain("d", "", [0, 1, 2])


def ring_problem(n=6):
    dcop = DCOP("ring")
    vs = [Variable(f"v{i}", D) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    for i in range(n):
        j = (i + 1) % n
        dcop.add_constraint(
            constraint_from_str(f"c{i}_{j}", f"1 if v{i} == v{j} else 0", vs)
        )
    return compile_dcop(dcop)


def test_checkpoint_roundtrip(tmp_path):
    path = str(tmp_path / "ck.npz")
    state = {
        "values": np.arange(4, dtype=np.int32),
        "nested": {"msgs": np.ones((3, 2), dtype=np.float32)},
    }
    save_checkpoint(path, state, 1.5, np.zeros(4, np.int32), 42, {"x": "y"})
    template = jax.tree_util.tree_map(np.zeros_like, state)
    got, best_cost, best_values, rounds, meta = load_checkpoint(path, template)
    assert best_cost == 1.5
    assert rounds == 42
    assert meta["x"] == "y"
    np.testing.assert_array_equal(got["values"], state["values"])
    np.testing.assert_array_equal(got["nested"]["msgs"], state["nested"]["msgs"])


def test_checkpoint_rejects_wrong_shape(tmp_path):
    path = str(tmp_path / "ck.npz")
    save_checkpoint(path, {"values": np.zeros(4)}, 0.0, np.zeros(4), 1)
    with pytest.raises(ValueError, match="shape"):
        load_checkpoint(path, {"values": np.zeros(5)})
    with pytest.raises(ValueError, match="misses"):
        load_checkpoint(path, {"other": np.zeros(4)})


@pytest.mark.parametrize("algo", ["dsa", "maxsum"])
def test_resume_matches_uninterrupted_run(tmp_path, algo):
    """checkpoint at round 32, resume → same result as a straight
    64-round run (same RNG stream — fold_in by absolute round index)."""
    problem = ring_problem()
    module = load_algorithm_module(algo)
    params = prepare_algo_params({}, module.algo_params)
    path = str(tmp_path / "ck.npz")

    full = run_batched(problem, module, params, rounds=64, seed=9,
                       chunk_size=32)
    part1 = run_batched(
        problem, module, params, rounds=32, seed=9, chunk_size=32,
        checkpoint_path=path,
    )
    assert part1.cycles == 32
    resumed = run_batched(
        problem, module, params, rounds=64, seed=9, chunk_size=32,
        checkpoint_path=path, resume=True,
    )
    assert resumed.cycles == 64
    assert resumed.assignment == full.assignment
    assert resumed.best_cost == full.best_cost


def test_resume_rejects_different_problem_instance(tmp_path):
    """A checkpoint from a structurally identical problem with different
    costs must be rejected (problem fingerprint, ADVICE r1 medium)."""
    module = load_algorithm_module("dsa")
    params = prepare_algo_params({}, module.algo_params)
    path = str(tmp_path / "ck.npz")

    problem_a = ring_problem()

    # same structure (6-var ring, same names/domains), different costs
    dcop = DCOP("ring")
    vs = [Variable(f"v{i}", D) for i in range(6)]
    for v in vs:
        dcop.add_variable(v)
    for i in range(6):
        j = (i + 1) % 6
        dcop.add_constraint(
            constraint_from_str(f"c{i}_{j}", f"5 if v{i} == v{j} else 0", vs)
        )
    problem_b = compile_dcop(dcop)

    run_batched(problem_a, module, params, rounds=8, seed=3, chunk_size=8,
                checkpoint_path=path)
    with pytest.raises(ValueError, match="different problem instance"):
        run_batched(problem_b, module, params, rounds=16, seed=3,
                    chunk_size=8, checkpoint_path=path, resume=True)


def test_solve_cli_checkpoint_resume(tmp_path):
    from tests.test_cli import run_cli

    yaml_file = tmp_path / "ring.yaml"
    lines = [
        "name: ring", "objective: min",
        "domains:", "  colors: {values: [0, 1, 2]}", "variables:",
    ]
    for i in range(5):
        lines.append(f"  v{i}: {{domain: colors}}")
    lines.append("constraints:")
    for i in range(5):
        j = (i + 1) % 5
        lines.append(f"  c{i}:")
        lines.append("    type: intention")
        lines.append(f"    function: 1 if v{i} == v{j} else 0")
    lines.append("agents: [a0, a1, a2, a3, a4]")
    yaml_file.write_text("\n".join(lines) + "\n")

    import json

    ck = tmp_path / "state.npz"
    r1 = run_cli(
        "solve", str(yaml_file), "-a", "dsa", "--rounds", "20",
        "--checkpoint", str(ck),
    )
    assert r1.returncode == 0, r1.stderr
    assert ck.exists()
    r2 = run_cli(
        "solve", str(yaml_file), "-a", "dsa", "--rounds", "40",
        "--checkpoint", str(ck), "--resume",
    )
    assert r2.returncode == 0, r2.stderr
    result = json.loads(r2.stdout)
    assert result["cycle"] == 40  # 20 restored + 20 new


def test_checkpoint_static_keys_roundtrip(tmp_path):
    """Direct save/load round-trip of the static_keys contract: save
    SKIPS leaves under a static key (pure problem-derived index data,
    wasted I/O), and load backfills them from the template — so the
    file is smaller AND the restored pytree is complete."""
    path = str(tmp_path / "ck.npz")
    state = {
        "values": np.arange(4, dtype=np.int32),
        "idx": np.arange(12, dtype=np.int32).reshape(3, 4),
    }
    save_checkpoint(
        path, state, 2.0, np.zeros(4, np.int32), 7, static_keys=("idx",)
    )
    with np.load(path) as data:
        assert "state/values" in data.files
        assert "state/idx" not in data.files  # skipped at save
    template = {
        "values": np.zeros(4, np.int32),
        "idx": state["idx"] + 0,  # init_state rebuilds this
    }
    got, best_cost, _, rounds, _ = load_checkpoint(
        path, template, static_keys=("idx",)
    )
    assert best_cost == 2.0 and rounds == 7
    np.testing.assert_array_equal(got["values"], state["values"])
    np.testing.assert_array_equal(got["idx"], state["idx"])  # backfilled
    # without static_keys on the load side the missing leaf is a real
    # error (a checkpoint from a different algorithm)
    with pytest.raises(ValueError, match="misses"):
        load_checkpoint(path, template)


def test_resume_backfills_static_state_keys(tmp_path):
    """A checkpoint written before an algorithm grew a new STATIC
    state key (pure problem-derived index data) must stay resumable:
    the missing leaf is backfilled from the fresh init_state template
    (mgm2 grew pe_inv in round 3)."""
    problem = ring_problem()
    module = load_algorithm_module("mgm2")
    params = prepare_algo_params({}, module.algo_params)
    path = str(tmp_path / "old.npz")

    full = run_batched(problem, module, params, rounds=64, seed=9,
                       chunk_size=32)
    part1 = run_batched(
        problem, module, params, rounds=32, seed=9, chunk_size=32,
        checkpoint_path=path,
    )
    assert part1.cycles == 32

    # simulate the old build's checkpoint: same file minus pe_inv
    with np.load(path) as data:
        stripped = {
            k: data[k] for k in data.files if k != "state/pe_inv"
        }
    np.savez(path, **stripped)

    resumed = run_batched(
        problem, module, params, rounds=64, seed=9, chunk_size=32,
        checkpoint_path=path, resume=True,
    )
    assert resumed.cycles == 64
    assert resumed.assignment == full.assignment
    assert resumed.best_cost == full.best_cost


def test_resume_array_built_problem(tmp_path):
    """Checkpoint/resume works for compile_from_arrays problems: the
    AutoNames/UniformLabels metadata fingerprints stably across
    processes (content-hash reprs), so a resume matches an
    uninterrupted run exactly."""
    import numpy as np

    from pydcop_tpu.algorithms import (
        load_algorithm_module,
        prepare_algo_params,
    )
    from pydcop_tpu.engine.batched import run_batched
    from pydcop_tpu.ops.compile import compile_from_arrays
    from pydcop_tpu.ops.generate import coloring_arrays

    sc, tb, un = coloring_arrays(60, seed=4)
    problem = compile_from_arrays(sc, tb, 3, unary=un)
    module = load_algorithm_module("dsa")
    params = prepare_algo_params({"variant": "B"}, module.algo_params)
    ckpt = str(tmp_path / "arr.npz")

    full = run_batched(
        problem, module, params, rounds=64, seed=2, chunk_size=16
    )
    run_batched(
        problem, module, params, rounds=32, seed=2, chunk_size=16,
        checkpoint_path=ckpt,
    )
    resumed = run_batched(
        problem, module, params, rounds=64, seed=2, chunk_size=16,
        checkpoint_path=ckpt, resume=True,
    )
    assert resumed.cost == full.cost
    np.testing.assert_array_equal(
        np.asarray(
            [resumed.assignment[n] for n in sorted(resumed.assignment)]
        ),
        np.asarray(
            [full.assignment[n] for n in sorted(full.assignment)]
        ),
    )
    # a different instance is still rejected via the fingerprint
    sc2, tb2, un2 = coloring_arrays(60, seed=5)
    other = compile_from_arrays(sc2, tb2, 3, unary=un2)
    import pytest

    with pytest.raises(ValueError, match="different problem"):
        run_batched(
            other, module, params, rounds=32, seed=2, chunk_size=16,
            checkpoint_path=ckpt, resume=True,
        )


def test_resume_restart_stack(tmp_path):
    """n_restarts=4: the whole [K, ...] state stack, per-restart best
    costs, and [K, n] best values round-trip through a checkpoint —
    interrupt at round 32, resume to 64, match the straight run."""
    problem = ring_problem()
    module = load_algorithm_module("dsa")
    params = prepare_algo_params({"variant": "B"}, module.algo_params)
    path = str(tmp_path / "ck.npz")

    full = run_batched(
        problem, module, params, rounds=64, seed=9, chunk_size=32,
        n_restarts=4,
    )
    run_batched(
        problem, module, params, rounds=32, seed=9, chunk_size=32,
        n_restarts=4, checkpoint_path=path,
    )
    resumed = run_batched(
        problem, module, params, rounds=64, seed=9, chunk_size=32,
        n_restarts=4, checkpoint_path=path, resume=True,
    )
    assert resumed.cycles == 64
    np.testing.assert_allclose(
        resumed.restart_costs, full.restart_costs, atol=1e-6
    )
    assert resumed.best_cost == full.best_cost
    assert resumed.assignment == full.assignment
    # a different K must be rejected (stack/RNG misalignment)
    with pytest.raises(ValueError, match="n_restarts"):
        run_batched(
            problem, module, params, rounds=64, seed=9, chunk_size=32,
            n_restarts=8, checkpoint_path=path, resume=True,
        )


def test_checkpoint_roundtrip_bf16_messages(tmp_path):
    """bf16 message state survives the .npz round-trip: np.savez
    stores ml_dtypes arrays as raw void records, and the loader
    reinterprets them via the template dtype (never converts)."""
    import __graft_entry__ as g
    from pydcop_tpu.algorithms import (
        load_algorithm_module,
        prepare_algo_params,
    )
    from pydcop_tpu.engine.batched import run_batched
    from pydcop_tpu.ops import compile_dcop

    dcop = g._make_coloring_dcop(24, degree=2, seed=3)
    problem = compile_dcop(dcop)
    module = load_algorithm_module("maxsum")
    params = prepare_algo_params({"msg_dtype": "bf16"}, module.algo_params)
    ck = str(tmp_path / "bf16.npz")
    full = run_batched(
        problem, module, params, rounds=16, seed=1, chunk_size=4
    )
    run_batched(
        problem, module, params, rounds=8, seed=1, chunk_size=4,
        checkpoint_path=ck,
    )
    resumed = run_batched(
        problem, module, params, rounds=16, seed=1, chunk_size=4,
        checkpoint_path=ck, resume=True,
    )
    assert resumed.best_cost == pytest.approx(full.best_cost, abs=1e-4)
