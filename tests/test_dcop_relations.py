import numpy as np
import pytest

from pydcop_tpu.dcop.objects import Domain, Variable
from pydcop_tpu.dcop.relations import (
    NAryFunctionRelation,
    NAryMatrixRelation,
    UnaryFunctionRelation,
    add_var_to_rel,
    assignment_cost,
    constraint_from_str,
    filter_assignment_dict,
    find_dependent_relations,
    optimal_cost_value,
)
from pydcop_tpu.utils.expressionfunction import ExpressionFunction
from pydcop_tpu.utils.simple_repr import from_repr, simple_repr

D2 = Domain("d2", "", [0, 1])
D3 = Domain("d3", "", ["R", "G", "B"])


def test_matrix_relation_basics():
    x, y = Variable("x", D2), Variable("y", D2)
    r = NAryMatrixRelation([x, y], [[0, 1], [1, 0]], name="neq")
    assert r.arity == 2
    assert r.shape == (2, 2)
    assert r(0, 1) == 1.0
    assert r(x=1, y=1) == 0.0
    assert r({"x": 1, "y": 0}) == 1.0


def test_matrix_relation_shape_mismatch():
    x, y = Variable("x", D2), Variable("y", D3)
    with pytest.raises(ValueError):
        NAryMatrixRelation([x, y], [[0, 1], [1, 0]])


def test_matrix_relation_set_value_immutable():
    x = Variable("x", D2)
    r = NAryMatrixRelation([x], [0, 0], name="u")
    r2 = r.set_value_for_assignment({"x": 1}, 5)
    assert r(x=1) == 0
    assert r2(x=1) == 5


def test_matrix_slice():
    x, y = Variable("x", D3), Variable("y", D3)
    m = np.arange(9).reshape(3, 3)
    r = NAryMatrixRelation([x, y], m, name="r")
    s = r.slice({"x": "G"})
    assert s.arity == 1
    assert s.scope_names == ["y"]
    assert s(y="R") == 3.0
    assert s(y="B") == 5.0


def test_matrix_join_shared_var():
    x, y, z = Variable("x", D2), Variable("y", D2), Variable("z", D2)
    r1 = NAryMatrixRelation([x, y], [[0, 1], [2, 3]], name="r1")
    r2 = NAryMatrixRelation([y, z], [[10, 20], [30, 40]], name="r2")
    j = r1.join(r2)
    assert set(j.scope_names) == {"x", "y", "z"}
    # cost(x, y, z) = r1(x, y) + r2(y, z)
    for xv in (0, 1):
        for yv in (0, 1):
            for zv in (0, 1):
                assert j(x=xv, y=yv, z=zv) == r1(xv, yv) + r2(yv, zv)


def test_matrix_join_axis_order_mismatch():
    # join where the shared variable sits at different axis positions
    x, y = Variable("x", D2), Variable("y", D3)
    r1 = NAryMatrixRelation([x, y], np.arange(6).reshape(2, 3), name="r1")
    r2 = NAryMatrixRelation([y, x], np.arange(6).reshape(3, 2) * 10, name="r2")
    j = r1.join(r2)
    for xv in (0, 1):
        for yv in ("R", "G", "B"):
            assert j(x=xv, y=yv) == r1(x=xv, y=yv) + r2(y=yv, x=xv)


def test_matrix_project_out():
    x, y = Variable("x", D2), Variable("y", D2)
    r = NAryMatrixRelation([x, y], [[5, 1], [2, 7]], name="r")
    p = r.project_out("y", mode="min")
    assert p.scope_names == ["x"]
    assert p(x=0) == 1 and p(x=1) == 2
    pmax = r.project_out("x", mode="max")
    assert pmax(y=0) == 5 and pmax(y=1) == 7


def test_argbest():
    x = Variable("x", D3)
    r = NAryMatrixRelation([x], [3, 1, 2], name="u")
    val, cost = r.argbest_for("x")
    assert val == "G" and cost == 1.0


def test_function_relation():
    x, y = Variable("x", D2), Variable("y", D2)
    r = NAryFunctionRelation(lambda a, b: a * 10 + b, [x, y], name="f")
    assert r(1, 0) == 10


def test_function_relation_slice():
    x, y = Variable("x", D2), Variable("y", D2)
    f = ExpressionFunction("x * 10 + y")
    r = NAryFunctionRelation(f, [x, y], name="f")
    s = r.slice({"x": 1})
    assert s.scope_names == ["y"]
    assert s(y=1) == 11


def test_as_matrix_tabulation():
    x, y = Variable("x", D3), Variable("y", D3)
    r = constraint_from_str("c", "10 if x == y else 0", [x, y])
    m = r.as_matrix()
    assert m.shape == (3, 3)
    for xv in D3:
        for yv in D3:
            assert m(x=xv, y=yv) == r(x=xv, y=yv)


def test_unary_function_relation():
    x = Variable("x", D2)
    r = UnaryFunctionRelation("u", x, lambda v: v * 3)
    assert r(1) == 3
    assert r(x=0) == 0


def test_constraint_from_str_scope():
    x, y, z = Variable("x", D2), Variable("y", D2), Variable("z", D2)
    r = constraint_from_str("c", "x + y", [x, y, z])
    assert set(r.scope_names) == {"x", "y"}
    with pytest.raises(ValueError):
        constraint_from_str("c", "x + unknown_var", [x, y])


def test_assignment_cost_and_filter():
    x, y = Variable("x", D2), Variable("y", D2)
    r1 = constraint_from_str("c1", "x + y", [x, y])
    r2 = constraint_from_str("c2", "10 * x", [x, y])
    a = {"x": 1, "y": 1, "zz": 5}
    assert assignment_cost({"x": 1, "y": 1}, [r1, r2]) == 12
    assert filter_assignment_dict(a, [x, y]) == {"x": 1, "y": 1}


def test_optimal_cost_value():
    from pydcop_tpu.dcop.objects import VariableWithCostFunc

    d = Domain("d", "", [0, 1, 2])
    v = VariableWithCostFunc("x", d, ExpressionFunction("(x - 1) ** 2"))
    val, cost = optimal_cost_value(v)
    assert val == 1 and cost == 0


def test_find_dependent_relations():
    x, y, z = Variable("x", D2), Variable("y", D2), Variable("z", D2)
    r1 = constraint_from_str("c1", "x + y", [x, y, z])
    r2 = constraint_from_str("c2", "y + z", [x, y, z])
    assert find_dependent_relations(x, [r1, r2]) == [r1]
    assert find_dependent_relations(y, [r1, r2]) == [r1, r2]


def test_add_var_to_rel():
    x, y = Variable("x", D2), Variable("y", D2)
    base = NAryMatrixRelation([x], [1, 2], name="b")
    ext = add_var_to_rel("e", base, y, lambda cost, v: cost + 100 * v)
    assert ext(x=1, y=1) == 102
    assert ext(x=0, y=0) == 1


def test_matrix_round_trip():
    x, y = Variable("x", D2), Variable("y", D2)
    r = NAryMatrixRelation([x, y], [[0, 1], [1, 0]], name="neq")
    r2 = from_repr(simple_repr(r))
    assert r2 == r


def test_unary_relation_round_trip():
    x = Variable("x", D2)
    r = UnaryFunctionRelation("u", x, ExpressionFunction("x * 2"))
    r2 = from_repr(simple_repr(r))
    assert r2(x=1) == 2 and r2.name == "u"


def test_matrix_hash_eq_contract():
    x, y = Variable("x", D2), Variable("y", D2)
    r1 = NAryMatrixRelation([x, y], [[0, 1], [1, 0]], name="a")
    r2 = NAryMatrixRelation([x, y], [[0, 1], [1, 0]], name="b")
    assert r1 == r2 and hash(r1) == hash(r2)
