"""Test configuration: run JAX on a virtual 8-device CPU mesh.

Multi-chip sharding logic is validated without TPU hardware via
``xla_force_host_platform_device_count`` (the driver separately dry-runs
the multi-chip path through ``__graft_entry__.dryrun_multichip``).

Note: on this image the ``axon`` TPU plugin overrides the
``JAX_PLATFORMS`` env var, so the CPU pin must go through
``jax.config.update`` before any backend is initialized.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
