"""Bit-parity of the fused Pallas Max-Sum kernels vs the XLA phases.

Runs in interpreter mode on the CPU test backend; on the real TPU the
same kernels are compiled by Mosaic (exercised by bench/profile runs).
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from pydcop_tpu.ops import pallas_maxsum  # noqa: E402


@pytest.fixture(scope="module")
def rng():
    return np.random.RandomState(3)


@pytest.mark.parametrize("d,m", [(3, 257), (2, 64), (5, 1000), (3, 2048)])
def test_factor_round_binary_matches_xla(rng, d, m):
    tab = jnp.asarray(rng.rand(d, d, m).astype(np.float32) * 10)
    q0 = jnp.asarray(rng.rand(d, m).astype(np.float32))
    q1 = jnp.asarray(rng.rand(d, m).astype(np.float32))

    # reference: the XLA phase from maxsum.step
    s = tab + q0.reshape(d, 1, m) + q1.reshape(1, d, m)
    ref0 = jnp.min(s, axis=1) - q0
    ref0 = ref0 - jnp.min(ref0, axis=0, keepdims=True)
    ref1 = jnp.min(s, axis=0) - q1
    ref1 = ref1 - jnp.min(ref1, axis=0, keepdims=True)

    r0, r1 = pallas_maxsum.factor_round_binary(
        tab, q0, q1, interpret=True
    )
    np.testing.assert_array_equal(np.asarray(r0), np.asarray(ref0))
    np.testing.assert_array_equal(np.asarray(r1), np.asarray(ref1))


@pytest.mark.parametrize("d,e", [(3, 500), (4, 4096), (2, 130)])
def test_q_update_matches_xla(rng, d, e):
    be = jnp.asarray(rng.rand(d, e).astype(np.float32) * 5)
    r = jnp.asarray(rng.rand(d, e).astype(np.float32))
    q = jnp.asarray(rng.rand(d, e).astype(np.float32))
    damping = 0.5

    ref = be - r
    ref = ref - jnp.min(ref, axis=0, keepdims=True)
    ref = damping * q + (1.0 - damping) * ref

    out = pallas_maxsum.q_update(
        be, r, q, jnp.asarray(damping), interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=1e-6, atol=1e-6
    )


def test_fused_step_disabled_on_cpu():
    # the CPU test backend must take the XLA path automatically
    assert not pallas_maxsum.available()


@pytest.mark.parametrize("d,m", [(3, 257), (2, 64), (5, 1000)])
def test_factor_round_binary_shared_matches_xla(rng, d, m):
    """Shared-table kernel (one [d, d] table in SMEM) must agree with
    the broadcast XLA phase bit-for-bit."""
    tab2 = jnp.asarray(rng.rand(d, d).astype(np.float32) * 10)
    q0 = jnp.asarray(rng.rand(d, m).astype(np.float32))
    q1 = jnp.asarray(rng.rand(d, m).astype(np.float32))

    s = tab2.reshape(d, d, 1) + q0.reshape(d, 1, m) + q1.reshape(1, d, m)
    ref0 = jnp.min(s, axis=1) - q0
    ref0 = ref0 - jnp.min(ref0, axis=0, keepdims=True)
    ref1 = jnp.min(s, axis=0) - q1
    ref1 = ref1 - jnp.min(ref1, axis=0, keepdims=True)

    r0, r1 = pallas_maxsum.factor_round_binary_shared(
        tab2, q0, q1, interpret=True
    )
    np.testing.assert_array_equal(np.asarray(r0), np.asarray(ref0))
    np.testing.assert_array_equal(np.asarray(r1), np.asarray(ref1))


@pytest.mark.parametrize("d,m", [(3, 257), (4, 512)])
def test_factor_round_binary_bf16_storage(rng, d, m):
    """bf16 message refs: arithmetic runs in f32 inside the kernel, so
    the result equals the f32 XLA phase computed on the UPCAST inputs,
    rounded once to bf16 at the write."""
    tab = jnp.asarray(rng.rand(d, d, m).astype(np.float32) * 10)
    q0 = jnp.asarray(
        rng.rand(d, m).astype(np.float32)
    ).astype(jnp.bfloat16)
    q1 = jnp.asarray(
        rng.rand(d, m).astype(np.float32)
    ).astype(jnp.bfloat16)

    q0f, q1f = q0.astype(jnp.float32), q1.astype(jnp.float32)
    s = tab + q0f.reshape(d, 1, m) + q1f.reshape(1, d, m)
    ref0 = jnp.min(s, axis=1) - q0f
    ref0 = (ref0 - jnp.min(ref0, axis=0, keepdims=True)).astype(
        jnp.bfloat16
    )
    ref1 = jnp.min(s, axis=0) - q1f
    ref1 = (ref1 - jnp.min(ref1, axis=0, keepdims=True)).astype(
        jnp.bfloat16
    )

    r0, r1 = pallas_maxsum.factor_round_binary(tab, q0, q1, interpret=True)
    assert r0.dtype == jnp.bfloat16 and r1.dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(r0.astype(jnp.float32)),
        np.asarray(ref0.astype(jnp.float32)),
    )
    np.testing.assert_array_equal(
        np.asarray(r1.astype(jnp.float32)),
        np.asarray(ref1.astype(jnp.float32)),
    )


def test_q_update_bf16_storage(rng):
    """bf16 q update: f32 math (the damping scalar's dtype), one bf16
    rounding at the output write."""
    d, e = 3, 500
    be = jnp.asarray(
        rng.rand(d, e).astype(np.float32) * 5
    ).astype(jnp.bfloat16)
    r = jnp.asarray(rng.rand(d, e).astype(np.float32)).astype(jnp.bfloat16)
    q = jnp.asarray(rng.rand(d, e).astype(np.float32)).astype(jnp.bfloat16)
    damping = 0.5

    bef, rf, qf = (
        be.astype(jnp.float32),
        r.astype(jnp.float32),
        q.astype(jnp.float32),
    )
    ref = bef - rf
    ref = ref - jnp.min(ref, axis=0, keepdims=True)
    ref = (damping * qf + (1.0 - damping) * ref).astype(jnp.bfloat16)

    out = pallas_maxsum.q_update(be, r, q, jnp.asarray(damping), interpret=True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(out.astype(jnp.float32)),
        np.asarray(ref.astype(jnp.float32)),
    )
