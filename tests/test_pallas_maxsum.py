"""Bit-parity of the fused Pallas Max-Sum kernels vs the XLA phases.

Runs in interpreter mode on the CPU test backend; on the real TPU the
same kernels are compiled by Mosaic (exercised by bench/profile runs).
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from pydcop_tpu.ops import pallas_maxsum  # noqa: E402


@pytest.fixture(scope="module")
def rng():
    return np.random.RandomState(3)


@pytest.mark.parametrize("d,m", [(3, 257), (2, 64), (5, 1000), (3, 2048)])
def test_factor_round_binary_matches_xla(rng, d, m):
    tab = jnp.asarray(rng.rand(d, d, m).astype(np.float32) * 10)
    q0 = jnp.asarray(rng.rand(d, m).astype(np.float32))
    q1 = jnp.asarray(rng.rand(d, m).astype(np.float32))

    # reference: the XLA phase from maxsum.step
    s = tab + q0.reshape(d, 1, m) + q1.reshape(1, d, m)
    ref0 = jnp.min(s, axis=1) - q0
    ref0 = ref0 - jnp.min(ref0, axis=0, keepdims=True)
    ref1 = jnp.min(s, axis=0) - q1
    ref1 = ref1 - jnp.min(ref1, axis=0, keepdims=True)

    r0, r1 = pallas_maxsum.factor_round_binary(
        tab, q0, q1, interpret=True
    )
    np.testing.assert_array_equal(np.asarray(r0), np.asarray(ref0))
    np.testing.assert_array_equal(np.asarray(r1), np.asarray(ref1))


@pytest.mark.parametrize("d,e", [(3, 500), (4, 4096), (2, 130)])
def test_q_update_matches_xla(rng, d, e):
    be = jnp.asarray(rng.rand(d, e).astype(np.float32) * 5)
    r = jnp.asarray(rng.rand(d, e).astype(np.float32))
    q = jnp.asarray(rng.rand(d, e).astype(np.float32))
    damping = 0.5

    ref = be - r
    ref = ref - jnp.min(ref, axis=0, keepdims=True)
    ref = damping * q + (1.0 - damping) * ref

    out = pallas_maxsum.q_update(
        be, r, q, jnp.asarray(damping), interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=1e-6, atol=1e-6
    )


def test_fused_step_disabled_on_cpu():
    # the CPU test backend must take the XLA path automatically
    assert not pallas_maxsum.available()


@pytest.mark.parametrize("d,m", [(3, 257), (2, 64), (5, 1000)])
def test_factor_round_binary_shared_matches_xla(rng, d, m):
    """Shared-table kernel (one [d, d] table in SMEM) must agree with
    the broadcast XLA phase bit-for-bit."""
    tab2 = jnp.asarray(rng.rand(d, d).astype(np.float32) * 10)
    q0 = jnp.asarray(rng.rand(d, m).astype(np.float32))
    q1 = jnp.asarray(rng.rand(d, m).astype(np.float32))

    s = tab2.reshape(d, d, 1) + q0.reshape(d, 1, m) + q1.reshape(1, d, m)
    ref0 = jnp.min(s, axis=1) - q0
    ref0 = ref0 - jnp.min(ref0, axis=0, keepdims=True)
    ref1 = jnp.min(s, axis=0) - q1
    ref1 = ref1 - jnp.min(ref1, axis=0, keepdims=True)

    r0, r1 = pallas_maxsum.factor_round_binary_shared(
        tab2, q0, q1, interpret=True
    )
    np.testing.assert_array_equal(np.asarray(r0), np.asarray(ref0))
    np.testing.assert_array_equal(np.asarray(r1), np.asarray(ref1))
