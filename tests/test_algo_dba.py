"""DBA / GDBA breakout tests: solving, QLM weight dynamics, modes,
and sharded (multi-chip emulated) parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pydcop_tpu.algorithms import load_algorithm_module, prepare_algo_params
from pydcop_tpu.api import solve
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import Domain, Variable
from pydcop_tpu.dcop.relations import constraint_from_str
from pydcop_tpu.engine.batched import run_batched
from pydcop_tpu.ops.compile import compile_dcop
from pydcop_tpu.parallel import make_mesh


def coloring_ring(n=10, colors=3):
    d = Domain("colors", "", list(range(colors)))
    dcop = DCOP(f"ring{n}")
    vs = [Variable(f"v{i}", d) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    for i in range(n):
        j = (i + 1) % n
        dcop.add_constraint(
            constraint_from_str(f"c{i}", f"1 if v{i} == v{j} else 0", vs)
        )
    return dcop


def two_color_triangle():
    """3-clique with 2 colors: unsatisfiable, optimum cost 1 — a
    guaranteed quasi-local-minimum generator."""
    d = Domain("c", "", [0, 1])
    dcop = DCOP("tri")
    vs = [Variable(f"v{i}", d) for i in range(3)]
    for v in vs:
        dcop.add_variable(v)
    for i in range(3):
        for j in range(i + 1, 3):
            dcop.add_constraint(
                constraint_from_str(
                    f"c{i}{j}", f"1 if v{i} == v{j} else 0", vs
                )
            )
    return dcop


def test_dba_solves_ring():
    r = solve(coloring_ring(12, 3), "dba", rounds=200, seed=1)
    assert r["cost"] == 0.0
    a = r["assignment"]
    for i in range(12):
        assert a[f"v{i}"] != a[f"v{(i + 1) % 12}"]


def test_dba_msg_accounting():
    r = solve(coloring_ring(10, 3), "dba", rounds=50, seed=0)
    assert r["msg_count"] == 50 * 2 * 2 * 10  # 2 msgs × Σdeg (=2·10)


def test_dba_weights_grow_at_qlm():
    """On the unsatisfiable triangle the search must hit a QLM and
    increase some constraint weight above its initial 1.0."""
    dcop = two_color_triangle()
    problem = compile_dcop(dcop)
    mod = load_algorithm_module("dba")
    params = prepare_algo_params({}, mod.algo_params)
    key = jax.random.PRNGKey(0)
    state = mod.init_state(problem, key, params)
    for i in range(30):
        state = mod.step(problem, state, jax.random.fold_in(key, i), params)
    assert float(jnp.max(state["weights"])) > 1.0
    # best achievable on the triangle is exactly 1 violated edge
    r = solve(dcop, "dba", rounds=50, seed=0)
    assert r["cost"] == 1.0


def test_dba_sharded_runs():
    dcop = coloring_ring(24, 3)
    mesh = make_mesh(8)
    problem = compile_dcop(dcop, n_shards=8)
    mod = load_algorithm_module("dba")
    params = prepare_algo_params({}, mod.algo_params)
    r = run_batched(problem, mod, params, rounds=120, seed=3, mesh=mesh)
    assert r.best_cost == 0.0


@pytest.mark.parametrize("modifier", ["A", "M"])
@pytest.mark.parametrize("violation", ["NZ", "NM", "MX"])
def test_gdba_modes_solve_ring(modifier, violation):
    r = solve(
        coloring_ring(10, 3),
        "gdba",
        {"modifier": modifier, "violation": violation},
        rounds=150,
        seed=2,
    )
    assert r["cost"] == 0.0


@pytest.mark.parametrize("imode", ["E", "R", "C", "T"])
def test_gdba_increase_modes_run(imode):
    r = solve(
        two_color_triangle(),
        "gdba",
        {"increase_mode": imode},
        rounds=60,
        seed=1,
    )
    assert r["cost"] == 1.0  # triangle optimum


def test_gdba_weight_regions():
    """increase_mode E touches exactly one cell; T the whole matrix."""
    dcop = two_color_triangle()
    problem = compile_dcop(dcop)
    mod = load_algorithm_module("gdba")
    key = jax.random.PRNGKey(4)

    def run(imode, rounds=25):
        params = prepare_algo_params(
            {"increase_mode": imode, "initial": "declared"}, mod.algo_params
        )
        state = mod.init_state(problem, key, params)
        for i in range(rounds):
            state = mod.step(
                problem, state, jax.random.fold_in(key, i), params
            )
        return np.asarray(state["w2"])

    w_e = run("E")
    w_t = run("T")
    # E only ever grows cells that were the current (violated) cell
    assert (w_e > 0).sum() < w_e.size
    # T grows whole matrices: any touched matrix is uniformly increased
    touched = w_t.sum(axis=1) > 0
    assert touched.any()
    for row in w_t[touched]:
        assert np.allclose(row, row[0])


def test_gdba_sharded_runs():
    dcop = coloring_ring(24, 3)
    mesh = make_mesh(8)
    problem = compile_dcop(dcop, n_shards=8)
    mod = load_algorithm_module("gdba")
    params = prepare_algo_params({}, mod.algo_params)
    r = run_batched(problem, mod, params, rounds=120, seed=5, mesh=mesh)
    assert r.best_cost == 0.0
