"""Tier-1 hook for graftlint (``tools/graftlint/``): contract
violations fail CI like any other test.

Two guards:

- the FULL-PACKAGE scan must report zero non-baselined findings (and
  zero stale baseline entries — fixed violations leave the baseline
  in the same PR), and must stay fast: the scan is stdlib-``ast``
  only, no jax import, so it is pinned under a ~10s budget to protect
  the thin 870s suite budget;
- SEEDED violations of each rule class — a module-level jax import on
  the jax-free surface, ``time.time()`` in ``faults/plan.py``, a
  registered fault kind dropped from one entry point's validation, an
  undocumented counter, a bare ``jax.jit`` outside the cache helpers
  — are caught by the corresponding rule.  Violations are seeded
  IN MEMORY (the ``scan(modules=…)`` seam) against the real package
  tree, so the test proves the real contract catches them without
  copying 179 files around.
"""

import ast
import os
import sys
import time

import pytest

pytestmark = pytest.mark.lint

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_TOOLS = os.path.join(_REPO, "tools")

if _TOOLS not in sys.path:
    sys.path.insert(0, _TOOLS)

from graftlint import (  # noqa: E402
    Module,
    default_config,
    diff_baseline,
    load_baseline,
    load_modules,
    scan,
)

# AST-only full-package scan, measured ~1.3s on this box; the budget
# is ~7x the recording — it catches "somebody made a rule quadratic",
# not scheduler noise, while protecting the suite's 870s ceiling
SCAN_BUDGET_SECONDS = 10.0

_BASELINE = os.path.join(_TOOLS, "graftlint_baseline.json")


def _config():
    return default_config(_REPO)


def test_full_package_scan_clean_and_fast():
    """Zero NEW findings, zero stale baseline entries, under budget."""
    t0 = time.perf_counter()
    findings = scan(_config())
    elapsed = time.perf_counter() - t0
    d = diff_baseline(findings, load_baseline(_BASELINE))
    assert d.new == [], "\n".join(
        f"{f.path}:{f.line}: [{f.rule}] {f.message}" for f in d.new
    )
    assert d.stale == [], (
        "baseline entries no longer matched — run "
        "`pydcop_tpu lint --update-baseline`: " + ", ".join(d.stale)
    )
    assert elapsed < SCAN_BUDGET_SECONDS, (
        f"full-package lint scan took {elapsed:.1f}s (budget "
        f"{SCAN_BUDGET_SECONDS}s) — a rule regressed from AST-linear"
    )


def test_baseline_entries_are_justified():
    """Every pinned finding carries a real one-line justification —
    a committed TODO means a violation was baselined unreviewed."""
    baseline = load_baseline(_BASELINE)
    assert baseline, "expected the repo's pinned findings"
    for key, justification in baseline.items():
        assert justification.strip() and not justification.startswith(
            "TODO"
        ), f"unjustified baseline entry: {key}"


def _mutate(modules, relpath, transform):
    mod = modules[relpath]
    text = transform(mod.text)
    modules[relpath] = Module(
        relpath=relpath,
        path=mod.path,
        text=text,
        tree=ast.parse(text),
    )


def test_seeded_violations_are_caught():
    """One seeded violation per rule class, all caught in one scan."""
    config = _config()
    modules = load_modules(config)

    # 1. module-level jax import on the declared jax-free surface
    _mutate(
        modules,
        "pydcop_tpu/api.py",
        lambda t: "import jax\n" + t,
    )
    # 2. wall-clock call in the seeded fault-plan module
    _mutate(
        modules,
        "pydcop_tpu/faults/plan.py",
        lambda t: t
        + (
            "\n\nimport time\n\n\n"
            "def _seeded_clock():\n"
            "    return time.time()\n"
        ),
    )
    # 3. a registered fault kind removed from one entry point's
    #    validation (the device check renamed away in `run`)
    _mutate(
        modules,
        "pydcop_tpu/commands/run.py",
        lambda t: t.replace(
            "device_faults_configured", "device_faults_elsewhere"
        ),
    )
    # 4. an undocumented counter + 5. a bare jax.jit outside the
    #    sanctioned cache helpers (batched.py imports jax already)
    _mutate(
        modules,
        "pydcop_tpu/engine/batched.py",
        lambda t: t
        + (
            "\n\ndef _seeded_violations(met):\n"
            '    met.inc("engine.seeded_undocumented")\n'
            "    return jax.jit(lambda x: x)\n"
        ),
    )

    findings = scan(config, modules=modules)
    d = diff_baseline(findings, load_baseline(_BASELINE))
    caught = {(f.rule, f.path) for f in d.new}
    assert ("jax-import-surface", "pydcop_tpu/api.py") in caught
    assert ("impure-call", "pydcop_tpu/faults/plan.py") in caught
    assert ("chaos-symmetry", "pydcop_tpu/commands/run.py") in caught
    assert ("metric-undocumented", "pydcop_tpu/engine/batched.py") in caught
    assert ("bare-jit", "pydcop_tpu/engine/batched.py") in caught
    # and each is attributed precisely, not as a co-located blur
    details = {(f.rule, f.detail) for f in d.new}
    assert ("impure-call", "time.time@_seeded_clock") in details
    assert ("chaos-symmetry", "category:device") in details
    assert (
        "metric-undocumented",
        "engine.seeded_undocumented",
    ) in details
    assert ("bare-jit", "jit@_seeded_violations") in details


def test_seeded_loop_body_jax_import_is_caught():
    """An import-time import hiding inside a module-level loop body
    (the conditional fallback-import pattern) still executes on every
    cold start — the surface rule must see through the loop."""
    config = _config()
    modules = load_modules(config)
    _mutate(
        modules,
        "pydcop_tpu/api.py",
        lambda t: t + "\n\nfor _lint_seed in range(1):\n    import jax\n",
    )
    # and the match-statement analogue (platform-dispatch pattern)
    _mutate(
        modules,
        "pydcop_tpu/cli.py",
        lambda t: t
        + "\n\nmatch 1:\n    case 1:\n        import jax\n",
    )
    findings = scan(config, modules=modules, rules=["jax-import-surface"])
    for rel in ("pydcop_tpu/api.py", "pydcop_tpu/cli.py"):
        assert any(
            f.path == rel and f.detail == "direct:jax"
            for f in findings
        ), (rel, findings)


def test_seeded_bare_jit_decorator_is_caught():
    """The plain `@jax.jit` decorator spelling (an Attribute, not a
    Call) outside the sanctioned helpers."""
    config = _config()
    modules = load_modules(config)
    _mutate(
        modules,
        "pydcop_tpu/engine/batched.py",
        lambda t: t
        + "\n\n@jax.jit\ndef _seeded_decorated(x):\n    return x\n",
    )
    findings = scan(config, modules=modules, rules=["bare-jit"])
    assert any(
        f.detail == "jit@_seeded_decorated" for f in findings
    ), findings


def test_seeded_transitive_jax_import_is_caught():
    """The harder variant of the surface rule: no jax import in
    sight, just a module-level hop into a jax-heavy module."""
    config = _config()
    modules = load_modules(config)
    _mutate(
        modules,
        "pydcop_tpu/api.py",
        lambda t: "from pydcop_tpu.engine.batched import run_batched\n"
        + t,
    )
    findings = scan(config, modules=modules, rules=["jax-import-surface"])
    hits = [f for f in findings if f.path == "pydcop_tpu/api.py"]
    assert hits, "transitive jax chain not detected"
    assert "pydcop_tpu/engine/batched.py" in hits[0].message


def test_seeded_inert_chaos_field_is_caught():
    """A new fault-parameter field that never flips `configured` is
    the parseable-but-inert bug class (PR 9's wire kinds)."""
    config = _config()
    modules = load_modules(config)
    _mutate(
        modules,
        "pydcop_tpu/faults/plan.py",
        lambda t: t.replace(
            "    transient: float = 0.0\n",
            "    transient: float = 0.0\n"
            "    reply_dup: float = 0.0\n",
        ),
    )
    findings = scan(
        config, modules=modules, rules=["chaos-inert-field"]
    )
    assert any(
        f.detail == "DeviceFaults.reply_dup" for f in findings
    ), findings
