"""Mixed-precision table packs (ISSUE 19, ``ops/semiring.py`` +
``algorithms/dpop.py`` + ``ops/membound.py``, ``docs/performance.md``
'Mixed-precision table packs'): the ``table_dtype`` axis must keep
the exact queries BIT-IDENTICAL to the f32 path (the certificate
ladder repairs uncertain low-precision nodes back to f32/f64), keep
the mass queries inside their honestly WIDENED error bounds, widen
the bnb slack conservatively, quantize int8 tables within the
reported grid bound, shrink the memory-bounded planner's per-cell
byte charge, and join the service's dispatch partition key.
"""

from __future__ import annotations

import importlib.util
import itertools
import os
import random

import numpy as np
import pytest

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
from pydcop_tpu.dcop.relations import NAryMatrixRelation

pytestmark = pytest.mark.semiring

_TOOL = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools",
    "recompile_guard.py",
)
_spec = importlib.util.spec_from_file_location(
    "recompile_guard_precision", _TOOL
)
_guard = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_guard)


# -- helpers ------------------------------------------------------------


def _hard_band(n, seed, d=4, arity=4, stride=2, cap=1.15, ties=False):
    """Chained overlap band with HARD over-sum caps (``+inf`` past
    ``cap x target``) — the same workload shape the bnb suite prunes.
    ``ties=True`` quantizes costs to a coarse grid so tables are
    tie-heavy: the adversarial case for a low-precision argmax
    certificate (near-ties are exactly what storage rounding flips)."""
    rnd = random.Random(seed)
    dom = Domain("d", "", list(range(d)))
    dcop = DCOP(f"px{seed}")
    vs = [Variable(f"v{i}", dom) for i in range(n)]
    for i, v in enumerate(vs):
        dcop.add_variable(v)
        dcop.add_constraint(
            NAryMatrixRelation(
                [v],
                np.arange(d, dtype=np.float64)
                * rnd.uniform(0.05, 0.3),
                name=f"u{i}",
            )
        )
    for m in range((n - arity) // stride + 1):
        scope = vs[m * stride:m * stride + arity]
        t = rnd.uniform(0.3, 0.8) * arity * (d - 1)
        mat = np.zeros((d,) * arity)
        for idx in itertools.product(range(d), repeat=arity):
            s = sum(idx)
            if s > cap * t:
                mat[idx] = np.inf
            else:
                c = abs(s - t)
                mat[idx] = round(c * 2) / 2.0 if ties else c
        dcop.add_constraint(
            NAryMatrixRelation(scope, mat, name=f"m{m}")
        )
    dcop.add_agents([AgentDef(f"a{i}") for i in range(n)])
    return dcop


def _overlap_secp():
    """The membound guard's fixed overlap-zone SECP — ONE builder
    shared with tools/recompile_guard.py so the cut-width assertions
    below can never drift onto a different workload."""
    return _guard._build_secp_overlap(12, 10, 3, seed=77)


# -- exact queries: bit parity across precisions ------------------------


@pytest.mark.parametrize("dtype", ["bf16", "int8"])
@pytest.mark.parametrize("seed,ties", [(1, True), (3, False)])
def test_dpop_and_map_bit_parity(dtype, seed, ties):
    """dpop and infer-map at bf16/int8 are BIT-IDENTICAL to f32 on
    tie-heavy and hard-capped (±inf) tables: the per-node certificate
    re-checks margins against the storage dtype's error and repairs
    uncertain cells at host f64, so storage rounding can never flip
    an argmax."""
    from pydcop_tpu.api import infer, solve

    dcop = _hard_band(10, seed, ties=ties)
    kw = dict(pad_policy="pow2")
    base = solve(dcop, "dpop", {"util_device": "always"}, **kw)
    low = solve(
        dcop, "dpop",
        {"util_device": "always", "table_dtype": dtype}, **kw
    )
    assert low["cost"] == base["cost"]
    assert low["assignment"] == base["assignment"]
    m32 = infer(dcop, "map", device="always")
    mlo = infer(dcop, "map", device="always", table_dtype=dtype)
    assert mlo["cost"] == m32["cost"]
    assert mlo["assignment"] == m32["assignment"]


@pytest.mark.parametrize("dtype", ["bf16", "int8"])
def test_kbest_bit_parity(dtype):
    """The k-best list — solutions AND costs, in order — matches f32
    exactly at low precision: each component's certificate is
    repaired per precision and every returned solution is f64
    re-evaluated."""
    from pydcop_tpu.api import infer

    dcop = _hard_band(9, 2, ties=True)
    off = infer(dcop, "kbest:5", device="always")
    low = infer(
        dcop, "kbest:5", device="always", table_dtype=dtype
    )
    assert low["solutions"] == off["solutions"]
    assert low["costs"] == off["costs"]
    assert low["k"] == off["k"]


# -- mass queries: honestly widened bounds ------------------------------


@pytest.mark.parametrize("dtype", ["bf16", "int8"])
def test_log_z_within_widened_bound(dtype):
    """log_z at low precision stays within its REPORTED error bound
    of the host-f64 answer, and that bound is strictly WIDER than the
    f32 device run's — honest accounting, not silent optimism.
    ``tol=inf`` keeps the low-precision tables active (the default
    tol demotes every uncertain mass node back to f32)."""
    from pydcop_tpu.api import infer

    dcop = _hard_band(9, 4)
    kw = dict(device="always", tol=float("inf"), pad_policy="pow2")
    host = infer(dcop, "log_z", device="never")
    dev32 = infer(dcop, "log_z", **kw)
    devlo = infer(dcop, "log_z", table_dtype=dtype, **kw)
    assert (
        abs(devlo["log_z"] - host["log_z"])
        <= devlo["error_bound"] + 1e-9
    )
    assert devlo["error_bound"] > dev32["error_bound"]


def test_default_tol_demotes_bf16_mass_nodes_to_f32():
    """Under the DEFAULT tol the repair ladder demotes every bf16
    mass node back to f32 — log_z is then identical to the f32 run
    and the demotions are counted in ``semiring.precision_repairs``."""
    from pydcop_tpu.api import infer

    dcop = _hard_band(9, 4)
    kw = dict(device="always", pad_policy="pow2")
    dev32 = infer(dcop, "log_z", **kw)
    devb = infer(dcop, "log_z", table_dtype="bf16", **kw)
    assert devb["log_z"] == dev32["log_z"]
    assert devb["error_bound"] == dev32["error_bound"]
    c = devb["telemetry"]["counters"]
    assert int(c.get("semiring.precision_repairs", 0)) >= 1, c


# -- int8 quantization grid ---------------------------------------------


@pytest.mark.parametrize("mag", [1e-6, 1.0, 1e6, 1e12])
def test_int8_round_trip_extreme_magnitudes(mag):
    """quantize/dequantize round-trips within the published grid
    bound ``int8_quant_bound`` at extreme magnitudes, and the ±inf
    reserved codes decode EXACTLY (hard constraints survive any
    scale)."""
    from pydcop_tpu.ops.padding import (
        dequantize_table_int8,
        int8_quant_bound,
        quantize_table_int8,
    )

    rnd = np.random.default_rng(11)
    a = (rnd.uniform(-1.0, 1.0, size=(4, 4)) * mag).astype(
        np.float32
    )
    a[0, 0] = np.inf
    a[1, 1] = -np.inf
    q, scale, offset = quantize_table_int8(a)
    back = dequantize_table_int8(q, scale, offset)
    finite = np.isfinite(a)
    bound = int8_quant_bound(float(np.abs(a[finite]).max()))
    assert np.all(
        np.abs(back[finite] - a[finite].astype(np.float64))
        <= bound * (1 + 1e-6)
    )
    assert back[0, 0] == np.inf and back[1, 1] == -np.inf


def test_int8_degenerate_constant_table_is_exact():
    from pydcop_tpu.ops.padding import (
        dequantize_table_int8,
        quantize_table_int8,
    )

    a = np.full((3, 3), 7.25, dtype=np.float32)
    q, scale, offset = quantize_table_int8(a)
    assert np.all(dequantize_table_int8(q, scale, offset) == 7.25)


# -- bnb slack stays conservative ---------------------------------------


@pytest.mark.parametrize("dtype", ["bf16", "int8"])
@pytest.mark.parametrize("seed", [1, 5])
def test_bnb_pruning_conservative_at_low_precision(dtype, seed):
    """bnb=on at low precision vs the unpruned host-f64 oracle: the
    slack widens by the storage dtype's eps (+ the int8 grid bound),
    so a row the pruned low-precision kernel discards provably cannot
    contain the optimum — cost AND assignment stay bit-identical."""
    from pydcop_tpu.api import solve

    dcop = _hard_band(10, seed, ties=True)
    oracle = solve(dcop, "dpop", {"util_device": "never"})
    pruned = solve(
        dcop, "dpop",
        {
            "util_device": "always", "bnb": "on",
            "table_dtype": dtype,
        },
        pad_policy="pow2",
    )
    assert pruned["cost"] == oracle["cost"]
    assert pruned["assignment"] == oracle["assignment"]


# -- memory-bounded planning at real byte width -------------------------


def test_membound_budgeted_bf16_matches_unbounded_f32():
    """The satellite's equivalence: budgeted + bf16 ≡ unbounded +
    f32 — the planner charges 2 bytes/cell so the same budget admits
    bigger tables, and the repair ladder keeps the min_sum result
    bit-identical anyway."""
    from pydcop_tpu.api import solve

    dcop = _overlap_secp()
    base = solve(dcop, "dpop", {"util_device": "never"})
    b = solve(
        dcop, "dpop",
        {"util_device": "always", "table_dtype": "bf16"},
        max_util_bytes=512, pad_policy="pow2",
    )
    assert b["cost"] == base["cost"]
    assert b["assignment"] == base["assignment"]
    assert b["membound"]["table_dtype"] == "bf16"


def test_membound_same_budget_smaller_cut_at_lower_precision():
    """The acceptance criterion, deterministic in-suite: at ONE fixed
    budget the planner's cut is strictly SMALLER at bf16 than at f32
    (fewer conditioned separator variables / lanes), because
    ``plan_cut`` sizes cells at the real per-dtype byte width — and
    every variant still lands on the same exact cost."""
    from pydcop_tpu.api import solve

    dcop = _overlap_secp()
    mbs = {}
    costs = set()
    for dt in ("f32", "bf16", "int8"):
        r = solve(
            dcop, "dpop",
            {"util_device": "never", "table_dtype": dt},
            max_util_bytes=512, pad_policy="pow2",
        )
        mbs[dt] = r["membound"]
        costs.add(r["cost"])
    assert len(costs) == 1  # budget/dtype never changes the answer
    assert mbs["bf16"]["cut_width"] < mbs["f32"]["cut_width"], mbs
    assert mbs["bf16"]["cut_lanes"] < mbs["f32"]["cut_lanes"], mbs
    assert (
        mbs["int8"]["cut_width"] <= mbs["bf16"]["cut_width"]
    ), mbs
    # the reported peaks are charged at the real byte width
    assert (
        mbs["f32"]["max_util_bytes"]
        == mbs["bf16"]["max_util_bytes"]
        == 512
    )


# -- vocabulary: one spelling, shared with msg_dtype --------------------


def test_dtype_vocabulary_is_shared_and_suggests_on_typo():
    """One parser (``ops/padding.as_table_dtype``) owns the precision
    vocabulary: aliases normalize, typos get a nearest-name
    suggestion, and maxsum's message-plane ``msg_dtype`` draws from
    the same spelling (bf16 only — messages are never int8)."""
    from pydcop_tpu.ops.padding import as_table_dtype

    assert as_table_dtype("bfloat16") == "bf16"
    assert as_table_dtype("float32") == "f32"
    assert as_table_dtype("i8") == "int8"
    assert as_table_dtype(None) == "f32"
    with pytest.raises(ValueError, match="bf16"):
        as_table_dtype("bf17")
    with pytest.raises(ValueError, match="int8"):
        as_table_dtype("int9")
    # the message-plane sibling rejects int8 with the narrowed list
    with pytest.raises(ValueError, match="f32"):
        as_table_dtype("int8", allowed=("f32", "bf16"))


def test_maxsum_msg_dtype_still_works_and_rejects_int8():
    from pydcop_tpu.api import solve

    dcop = _hard_band(8, 6, cap=10.0)  # soft band: maxsum-friendly
    r = solve(
        dcop, "maxsum", {"msg_dtype": "bf16"}, rounds=12, seed=0
    )
    assert r["assignment"]
    with pytest.raises(ValueError, match="msg_dtype|f32"):
        solve(
            dcop, "maxsum", {"msg_dtype": "int8"}, rounds=4, seed=0
        )


# -- service: dtype joins the partition key and rides the wire ----------


@pytest.mark.service
def test_service_dtype_joins_infer_partition_key():
    """Two same-query infers differing ONLY in table_dtype land in
    one tick but dispatch as TWO partitions — the dtype is part of
    ``_infer_group_key``, so mixed-precision traffic never merges
    into one sweep with a single dtype."""
    from pydcop_tpu.engine.service import SolverService

    dcop = _hard_band(8, 1)
    with SolverService(
        max_batch=2, max_wait=10.0, autostart=False
    ) as svc:
        p32 = svc.submit_infer(dcop, "map", device="never")
        pb = svc.submit_infer(
            dcop, "map", device="never", table_dtype="bf16"
        )
        r32, rb = p32.result(timeout=300), pb.result(timeout=300)
        stats = svc.stats()
    assert r32["cost"] == rb["cost"]
    assert r32["assignment"] == rb["assignment"]
    assert stats["ticks"] == 1, stats
    assert stats["dispatches"] == 2, stats


@pytest.mark.service
def test_service_wire_round_trip_carries_table_dtype():
    """table_dtype rides the wire protocol end to end: an infer frame
    and a solve frame both carry it, results match the in-process
    calls bit-for-bit, and a bad spelling fails THIS call with the
    nearest-name suggestion without killing the connection."""
    from pydcop_tpu.api import infer
    from pydcop_tpu.dcop.yamldcop import dcop_yaml
    from pydcop_tpu.engine.service import (
        ServiceClient,
        ServiceError,
        ServiceServer,
        SolverService,
    )

    dcop = _hard_band(8, 1)
    yaml_text = dcop_yaml(dcop)
    ref = infer(dcop, "map", device="never", table_dtype="bf16")
    with SolverService(max_wait=0.05) as svc:
        with ServiceServer(svc, port=0) as server:
            with ServiceClient(server.address) as cli:
                out = cli.infer(
                    yaml_text, "map", device="never",
                    table_dtype="bf16",
                )
                assert out["cost"] == ref["cost"]
                assert out["assignment"] == ref["assignment"]
                s = cli.solve(
                    yaml_text, "dpop", {"util_device": "never"},
                    table_dtype="int8",
                )
                assert s["cost"] == ref["cost"]
                with pytest.raises(
                    (ServiceError, ValueError), match="bf16"
                ):
                    cli.infer(
                        yaml_text, "map", table_dtype="bf17"
                    )
                assert cli.ping()  # connection survived the error


if __name__ == "__main__":
    pytest.main([__file__, "-q"])
