"""End-to-end serving observability (ISSUE 14, docs/observability.md
"Serving observability"): wire-propagated request traces stitched
across client and server trace files, per-request phase breakdowns
summing to the client-observed latency, the always-on flight recorder
dumping on quarantine/shed triggers with no trace file configured, and
the live /metrics + /healthz exporter under a concurrent burst.

Timing discipline matches tests/test_service.py: deterministic ticks
come from ``max_batch == number of submitted requests`` with a long
``max_wait``; the shared ``pow2:16`` pad policy + rounds/chunk shapes
ride the runner compiles the service tests already paid in-suite.
"""

import json
import os
import subprocess
import sys
import threading
import time

import pytest

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
from pydcop_tpu.dcop.relations import constraint_from_str
from pydcop_tpu.engine.service import (
    ServiceClient,
    ServiceServer,
    SolverService,
)
from pydcop_tpu.telemetry import get_metrics, session
from pydcop_tpu.telemetry.context import mint_trace_id
from pydcop_tpu.telemetry.export import (
    MetricsExporter,
    http_get,
    parse_prometheus_text,
    prometheus_text,
)
from pydcop_tpu.telemetry.flightrec import load_dump
from pydcop_tpu.telemetry.summary import (
    PHASE_KEYS,
    load_trace,
    stitch_requests,
)

pytestmark = [pytest.mark.telemetry, pytest.mark.service]

D = Domain("d", "", [0, 1, 2])

KW = dict(rounds=24, chunk_size=24)
PAD = "pow2:16"


def ring_yaml(n=6, name="ring"):
    return (
        f"name: {name}\n"
        "objective: min\n"
        "domains:\n"
        "  colors: {values: [0, 1, 2]}\n"
        "variables:\n"
        + "".join(f"  v{i}: {{domain: colors}}\n" for i in range(n))
        + "constraints:\n"
        + "".join(
            f"  c{i}: {{type: intention, "
            f"function: '1 if v{i} == v{(i + 1) % n} else 0'}}\n"
            for i in range(n)
        )
        + "agents: [a1]\n"
    )


RING_YAML = ring_yaml()


def _ring_dcop(n=6, name="ring"):
    dcop = DCOP(name)
    vs = [Variable(f"v{i}", D) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    for i in range(n):
        dcop.add_constraint(
            constraint_from_str(
                f"c{i}", f"1 if v{i} == v{(i + 1) % n} else 0", vs
            )
        )
    dcop.add_agents([AgentDef(f"a{i}") for i in range(n)])
    return dcop


def _drop_scrape_counter(snapshot):
    """The scrape endpoint counts itself (`telemetry.scrapes`), so a
    scrape can never equal a snapshot taken around it on that one
    counter — compare everything else."""
    out = dict(snapshot)
    out["counters"] = {
        k: v
        for k, v in snapshot.get("counters", {}).items()
        if k != "telemetry.scrapes"
    }
    return out


# -- live export: /metrics under a concurrent burst, /healthz ------------


def test_metrics_endpoint_live_burst_parses_and_matches_snapshot():
    """Acceptance: GET /metrics DURING a live 32-client wire burst
    parses as Prometheus text exposition, and once the burst settles
    the exposition matches a registry snapshot taken in the same
    quiet window."""
    n = 32
    yamls = [ring_yaml(5 + i % 3, name=f"q{i}") for i in range(n)]
    results = [None] * n
    errors = []
    live_scrapes = []
    with session() as tel:
        with SolverService(
            pad_policy=PAD, max_batch=n, max_wait=0.25
        ) as svc:
            with ServiceServer(svc, port=0) as server:
                with MetricsExporter(
                    tel.metrics.snapshot, svc.health
                ) as ex:
                    url = "http://%s:%d" % ex.address
                    health = json.loads(http_get(url + "/healthz"))
                    assert health["status"] == "ok"

                    def client(i):
                        try:
                            with ServiceClient(
                                server.address, client_id=f"m{i}",
                                retry_window=30.0,
                            ) as cli:
                                results[i] = cli.solve(
                                    yamls[i], "mgm", seed=i, **KW
                                )
                        except Exception as e:  # noqa: BLE001
                            errors.append((i, repr(e)))

                    threads = [
                        threading.Thread(
                            target=client, args=(i,), daemon=True
                        )
                        for i in range(n)
                    ]
                    for t in threads:
                        t.start()
                    # scrape WHILE the burst is in flight: every
                    # response must parse (strict parser)
                    while any(t.is_alive() for t in threads):
                        live_scrapes.append(
                            parse_prometheus_text(
                                http_get(url + "/metrics")
                            )
                        )
                        time.sleep(0.01)
                    for t in threads:
                        t.join(60)
                    assert not errors, errors
                    # settle, then demand an exact match against a
                    # snapshot bracketing the scrape (same tick
                    # window: no request in flight, counters quiet)
                    matched = False
                    for _ in range(50):
                        snap_before = _drop_scrape_counter(
                            tel.metrics.snapshot()
                        )
                        text = http_get(url + "/metrics")
                        snap_after = _drop_scrape_counter(
                            tel.metrics.snapshot()
                        )
                        if snap_before == snap_after:
                            got = parse_prometheus_text(text)
                            got.pop(
                                "pydcop_telemetry_scrapes_total",
                                None,
                            )
                            assert got == parse_prometheus_text(
                                prometheus_text(snap_before)
                            )
                            matched = True
                            break
                        time.sleep(0.02)
                    assert matched, "registry never quiesced"
    assert all(r is not None for r in results)
    assert len(live_scrapes) >= 1
    # the burst's own counters were visible live
    final = live_scrapes[-1]
    assert final.get("pydcop_service_requests_total", 0) <= n
    assert (
        get_metrics().enabled is False
    )  # session closed cleanly behind us


def test_healthz_flips_to_draining_during_graceful_shutdown():
    """Acceptance: /healthz reports ok -> draining (the moment the
    graceful drain starts, while the in-flight tick finishes) ->
    drained."""
    with session() as tel:
        svc = SolverService(pad_policy=PAD, max_batch=1, max_wait=0.0)
        ex = MetricsExporter(tel.metrics.snapshot, svc.health)
        url = "http://%s:%d" % ex.address
        try:
            assert (
                json.loads(http_get(url + "/healthz"))["status"]
                == "ok"
            )
            # a deliberately long dispatch (fresh chunk shape => it
            # also pays its runner compile inside the tick) keeps the
            # worker busy while close() drains
            pending = svc.submit(
                ring_yaml(12, name="long"), "mgm", {},
                rounds=4000, chunk_size=100,
            )
            deadline = time.time() + 120
            while svc.stats()["ticks"] < 1:
                assert time.time() < deadline
                time.sleep(0.005)
            closer = threading.Thread(target=svc.close)
            closer.start()
            saw_draining = False
            deadline = time.time() + 120
            while closer.is_alive() and time.time() < deadline:
                h = json.loads(http_get(url + "/healthz"))
                if h["status"] == "draining":
                    saw_draining = True
                    break
                time.sleep(0.002)
            closer.join(120)
            assert saw_draining, "never observed status=draining"
            h = json.loads(http_get(url + "/healthz"))
            assert h["status"] == "drained"
            assert h["queue_depth"] == 0
            # the drained request still delivered ("finish and
            # deliver" — the drain completed its tick)
            assert pending.result(1)["status"] in (
                "finished", "degraded",
            )
        finally:
            ex.close()
            svc.close()


def test_top_one_shot_snapshot(capsys):
    from pydcop_tpu.cli import main

    with session() as tel:
        m = get_metrics()
        m.inc("service.requests", 3)
        m.inc("service.shed")
        m.observe("service.latency_s", 0.02)
        with MetricsExporter(
            tel.metrics.snapshot,
            lambda: {
                "status": "ok", "queue_depth": 0, "inflight": 0,
                "sessions": 0,
            },
        ) as ex:
            rc = main(
                [
                    "top", "%s:%d" % ex.address,
                    "--count", "1", "--interval", "0.01",
                ]
            )
    assert rc == 0
    out = capsys.readouterr().out
    assert "status=ok" in out
    assert "requests" in out and "latency_s" in out
    # a dead address is a clean usage error, not a hang
    with pytest.raises(SystemExit, match="cannot scrape"):
        main(
            ["top", "127.0.0.1:1", "--count", "1",
             "--interval", "0.01"]
        )


# -- flight recorder: dumps with NO trace file ---------------------------


def test_flight_dump_on_quarantine_and_deadline_shed(tmp_path):
    """Acceptance: a nan_inject-quarantined and a deadline-shed
    request each produce a flight-recorder dump containing the
    triggering request's spans — with NO trace file configured."""
    fpath = str(tmp_path / "flight.json")
    dcops = [_ring_dcop(5 + i % 3, name=f"q{i}") for i in range(8)]
    kw = dict(rounds=24, chunk_size=12)
    with session() as tel:  # no trace path: ring only
        assert tel.tracer.path is None
        with SolverService(
            pad_policy=PAD, max_batch=8, max_wait=30.0,
            autostart=False, chaos="nan_inject=1:2", chaos_seed=3,
            flight_dump=fpath,
        ) as svc:
            pendings = [
                svc.submit(d, "mgm", {}, seed=7, **kw) for d in dcops
            ]
            results = [p.result(timeout=300) for p in pendings]
            degraded = [
                r for r in results if r["status"] == "degraded"
            ]
            assert len(degraded) == 1
            # read the dump BEFORE close(): the drain trigger will
            # overwrite it
            doc = load_dump(fpath)
            assert doc["trigger"] == "quarantine"
            assert doc["trace_id"] == degraded[0]["trace"]
            tagged = [
                r
                for r in doc["records"]
                if r.get("kind") in ("span", "event")
                and (
                    (r.get("args") or {}).get("trace")
                    == doc["trace_id"]
                    or doc["trace_id"]
                    in ((r.get("args") or {}).get("trace") or ())
                )
            ]
            # the triggering request's own spans are on the ring:
            # its queue-wait + request spans and the group dispatch
            names = {r.get("name") for r in tagged}
            assert "service.request" in names
            assert "service.dispatch" in names
            # the injected fault itself rode the ring too
            assert any(
                r.get("name") == "nan_inject"
                for r in doc["records"]
                if r.get("kind") == "event"
            )
        # the drain overwrote the dump, trigger front and center
        assert load_dump(fpath)["trigger"] == "drain"

    # deadline shed: stopped worker, learned tick estimate, a
    # deadline the service knows it cannot meet
    fpath2 = str(tmp_path / "flight2.json")
    with session():
        svc = SolverService(
            pad_policy=PAD, max_batch=4, max_wait=30.0,
            autostart=False, flight_dump=fpath2,
        )
        for i in range(4):
            svc.submit(
                ring_yaml(name=f"r{i}"), "mgm", {}, seed=i, **KW
            )
        svc._tick_med = 1.0
        shed = svc.submit(
            RING_YAML, "mgm", {}, timeout=0.5, seed=9, **KW
        ).result(5)
        assert shed["status"] == "shed"
        assert shed["shed_reason"] == "deadline"
        doc = load_dump(fpath2)
        assert doc["trigger"] == "shed"
        assert doc["trace_id"] == shed["trace"]
        # the shed event carries the triggering trace id
        assert any(
            r.get("name") == "service-shed"
            and (r.get("args") or {}).get("trace") == shed["trace"]
            for r in doc["records"]
        )
        # dump throttling: a shed STORM must not serialize the ring
        # once per rejected request — triggers inside the min
        # interval are suppressed (the first dump already captured
        # the episode), and the window reopening dumps again
        shed2 = svc.submit(
            RING_YAML, "mgm", {}, timeout=0.5, seed=10, **KW
        ).result(5)
        assert shed2["status"] == "shed"
        assert load_dump(fpath2)["trace_id"] == shed["trace"]
        svc._flight_last = 0.0  # the interval elapses
        shed3 = svc.submit(
            RING_YAML, "mgm", {}, timeout=0.5, seed=11, **KW
        ).result(5)
        assert load_dump(fpath2)["trace_id"] == shed3["trace"]
        with svc._cond:
            svc._queue.clear()  # discard without dispatching
        svc.close()


# -- the end-to-end wire stitch acceptance -------------------------------


def _spawn_serve(args, env):
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "pydcop_tpu", "serve",
            "--port", "0", *args,
        ],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        env=env,
        text=True,
    )
    line = proc.stdout.readline()
    return proc, json.loads(line)


def test_e2e_wire_stitch_conn_drop_and_phase_breakdown(tmp_path):
    """THE tentpole acceptance: a wire client request that survives a
    conn_drop retry under chaos yields ONE correlated timeline
    (client attempt spans + server spans sharing the trace id),
    `trace-summary --requests` prints its phase breakdown, and a
    clean request's phase breakdown sums to within 5% of the
    client-measured latency."""
    from pydcop_tpu.cli import main

    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    server_trace = str(tmp_path / "server.jsonl")
    client_trace = str(tmp_path / "client.jsonl")
    cache = str(tmp_path / "xla-cache")
    # conn_drop=1:3 — per connection the first three replies are
    # exempt, every later computed reply is dropped before sending:
    # conn 1 carries ping(1) / warm solve(2) / measured solve(3)
    # untouched, the 4th reply (the chaos solve) drops and replays
    # from the reply cache on the retry's fresh connection (seq 1,
    # exempt again)
    proc, head = _spawn_serve(
        [
            "--max_wait", "0.0", "--max_batch", "1",
            "--compile_cache", cache,
            "--trace", server_trace,
            "--chaos", "conn_drop=1:3", "--chaos_seed", "5",
        ],
        env,
    )
    ring = ring_yaml(32, name="stitch")
    kw = dict(chunk_size=300, timeout=600)
    lat = None
    try:
        with session(client_trace):
            with ServiceClient(
                head["serving"], client_id="e2e", retry_window=60.0,
            ) as cli:
                assert cli.ping()  # rid 1
                cli.solve(ring, "mgm", rounds=300, seed=1, **kw)  # rid 2: warms the chunk-300 runner
                t0 = time.perf_counter()
                r = cli.solve(  # rid 3: the measured clean request
                    ring, "mgm", rounds=12000, seed=1, **kw
                )
                lat = time.perf_counter() - t0
                dropped = cli.solve(  # rid 4: the conn_drop survivor
                    ring, "mgm", rounds=300, seed=2, **kw
                )
                cli.shutdown()  # rid 5
        out, err = proc.communicate(timeout=180)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode == 0, err
    assert r["status"] == "finished"
    assert dropped["status"] == "finished"

    stitched = stitch_requests(
        [load_trace(client_trace), load_trace(server_trace)]
    )
    tid_clean = mint_trace_id("e2e", 3)
    tid_drop = mint_trace_id("e2e", 4)
    assert r["trace"] == tid_clean
    assert dropped["trace"] == tid_drop

    # ONE correlated timeline for the conn_drop survivor: >= 2 client
    # attempts, exactly ONE server solve (no phantom re-solve), the
    # replayed reply visible, spans from BOTH files joined
    surv = stitched[tid_drop]
    assert surv["attempts"] >= 2
    assert surv["server_requests"] == 1
    assert surv["replays"] >= 1
    srcs = {e["src"] for e in surv["timeline"]}
    assert srcs == {0, 1}  # client file AND server file
    names = {e["name"] for e in surv["timeline"]}
    assert {
        "client.request", "client.attempt", "service.queue-wait",
        "service.request", "service.dispatch", "service-replay",
    } <= names

    # the clean request: phase breakdown present in the reply AND the
    # stitched timeline, summing to within 5% of the client latency
    clean = stitched[tid_clean]
    assert clean["attempts"] == 1 and clean["server_requests"] == 1
    phases = r["phases"]
    assert set(PHASE_KEYS) <= set(phases)
    total = sum(float(phases[k]) for k in PHASE_KEYS)
    assert total <= lat
    gap = (lat - total) / lat
    assert gap < 0.05, (phases, lat, gap)
    assert clean["phases"] is not None
    assert clean["client_latency_s"] == pytest.approx(lat, rel=0.2)

    # the CLI prints the correlated timelines
    assert (
        main(
            [
                "trace-summary", client_trace, server_trace,
                "--requests",
            ]
        )
        == 0
    )


if __name__ == "__main__":
    pytest.main([__file__, "-q"])
