"""Round-trip tests for the simple_repr serialization layer."""

import json

import pytest

from pydcop_tpu.utils.simple_repr import (
    SimpleRepr,
    SimpleReprException,
    from_repr,
    simple_repr,
)


class Point(SimpleRepr):
    def __init__(self, x, y):
        self._x = x
        self._y = y


class Named(SimpleRepr):
    def __init__(self, name, tags=None):
        self._name = name
        self._tags = tags or []


def test_primitives_pass_through():
    for v in (None, True, 3, 2.5, "abc"):
        assert simple_repr(v) == v
        assert from_repr(simple_repr(v)) == v


def test_object_round_trip():
    p = Point(1, 2.5)
    r = simple_repr(p)
    p2 = from_repr(r)
    assert isinstance(p2, Point)
    assert p2._x == 1 and p2._y == 2.5


def test_nested_containers_round_trip():
    n = Named("a", tags=["x", "y"])
    obj = {"k": [n, (1, 2)], 3: {4, 5}}
    r = simple_repr(obj)
    # must be JSON-serializable (the wire format requirement)
    json.dumps(r)
    obj2 = from_repr(r)
    assert obj2["k"][0]._name == "a"
    assert obj2["k"][0]._tags == ["x", "y"]
    assert obj2["k"][1] == (1, 2)
    assert obj2[3] == {4, 5}


def test_missing_attribute_raises():
    class Bad(SimpleRepr):
        def __init__(self, a):
            self.b = a

    with pytest.raises(SimpleReprException):
        simple_repr(Bad(1))


def test_unserializable_raises():
    with pytest.raises(SimpleReprException):
        simple_repr(object())
