"""SyncBB: exactness (vs DPOP), ordering, accounting."""

import random

import pytest

from pydcop_tpu.api import solve
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import Domain, Variable
from pydcop_tpu.dcop.relations import (
    NAryMatrixRelation,
    constraint_from_str,
)
from pydcop_tpu.graphs import ordered_graph


def coloring_ring(n=8, colors=3):
    d = Domain("colors", "", list(range(colors)))
    dcop = DCOP(f"ring{n}")
    vs = [Variable(f"v{i}", d) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    for i in range(n):
        j = (i + 1) % n
        dcop.add_constraint(
            constraint_from_str(f"c{i}", f"1 if v{i} == v{j} else 0", vs)
        )
    return dcop


def random_dcop(n=7, d_size=3, n_cons=10, seed=0, objective="min"):
    rnd = random.Random(seed)
    d = Domain("d", "", list(range(d_size)))
    dcop = DCOP("rand", objective=objective)
    vs = [Variable(f"x{i}", d) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    seen = set()
    for c in range(n_cons):
        i, j = rnd.sample(range(n), 2)
        if (min(i, j), max(i, j)) in seen:
            continue
        seen.add((min(i, j), max(i, j)))
        m = NAryMatrixRelation(
            [vs[i], vs[j]],
            [[rnd.randint(0, 9) for _ in range(d_size)] for _ in range(d_size)],
            name=f"c{c}",
        )
        dcop.add_constraint(m)
    return dcop


def test_syncbb_solves_ring_optimally():
    r = solve(coloring_ring(8, 3), "syncbb")
    assert r["status"] == "finished"
    assert r["cost"] == 0.0
    assert r["msg_count"] > 0


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_syncbb_matches_dpop_on_random_problems(seed):
    dcop = random_dcop(seed=seed)
    r_bb = solve(dcop, "syncbb")
    r_dpop = solve(random_dcop(seed=seed), "dpop")
    assert r_bb["cost"] == pytest.approx(r_dpop["cost"])


def test_syncbb_maximize():
    dcop = random_dcop(seed=5, objective="max")
    r_bb = solve(dcop, "syncbb")
    r_dpop = solve(random_dcop(seed=5, objective="max"), "dpop")
    assert r_bb["cost"] == pytest.approx(r_dpop["cost"])
    # max-mode must not just return the min solution
    r_min = solve(random_dcop(seed=5, objective="min"), "dpop")
    assert r_bb["cost"] >= r_min["cost"]


def test_ordered_graph_explicit_ordering():
    dcop = coloring_ring(5, 3)
    names = [f"v{i}" for i in range(5)]
    g = ordered_graph.build_computation_graph(
        dcop, ordering=list(reversed(names))
    )
    assert g.ordering == list(reversed(names))
    assert g.next_node("v1") == "v0"
    assert g.previous_node("v0") == "v1"
    with pytest.raises(ValueError):
        ordered_graph.build_computation_graph(dcop, ordering=names[:-1])


def test_syncbb_footprints():
    from pydcop_tpu.algorithms import load_algorithm_module

    mod = load_algorithm_module("syncbb")
    g = ordered_graph.build_computation_graph(coloring_ring(5, 3))
    n0 = g.node("v0")
    n4 = g.node("v4")
    assert mod.computation_memory(n4) > mod.computation_memory(n0)
    assert mod.communication_load(n4, "v3") > mod.communication_load(n0, "v1")
