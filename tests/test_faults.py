"""Deterministic fault injection (pydcop_tpu/faults) + the message
planes' transient-fault tolerance: seeded FaultPlan determinism, the
ChaosCommunicationLayer's injected-event replay guarantee, the TCP
plane's bounded reconnect/resend with receiver dedupe, and the
orchestrator's heal-vs-degrade split around the grace window
(docs/faults.md)."""

import os
import socket
import subprocess
import sys
import threading
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _ring_yaml(n=8, agents=("a1", "a2"), colors=3):
    lines = [
        "name: ring",
        "objective: min",
        "domains:",
        "  colors: {values: ["
        + ", ".join(str(c) for c in range(colors))
        + "]}",
        "variables:",
    ]
    for i in range(n):
        lines.append(f"  v{i}: {{domain: colors}}")
    lines.append("constraints:")
    for i in range(n):
        j = (i + 1) % n
        lines.append(f"  c{i}:")
        lines.append("    type: intention")
        lines.append(f"    function: 1 if v{i} == v{j} else 0")
    lines.append(f"agents: [{', '.join(agents)}]")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# FaultPlan: spec parsing + determinism
# ---------------------------------------------------------------------------


SPEC = (
    "drop=0.2,dup=0.1,reorder=0.1,delay=0.2:0.01,"
    "a1>a2:drop=0.5,partition=a1-a3@0.5+2,crash=a9@1.5"
)


@pytest.mark.chaos
def test_fault_plan_same_seed_identical_decisions():
    """The determinism core: two plans from the same (spec, seed) make
    byte-identical per-link decision sequences; a different seed makes
    a different sequence (the faults actually depend on the seed)."""
    from pydcop_tpu.faults import FaultPlan

    a = FaultPlan.from_spec(SPEC, 42)
    b = FaultPlan.from_spec(SPEC, 42)
    for link in (("a1", "a2"), ("x", "y"), ("a2", "a1")):
        assert a.decisions(*link, 500) == b.decisions(*link, 500)
    c = FaultPlan.from_spec(SPEC, 43)
    assert a.decisions("x", "y", 500) != c.decisions("x", "y", 500)
    # per-link override beats the default
    n_over = sum(d.drop for d in a.decisions("a1", "a2", 400))
    n_def = sum(d.drop for d in a.decisions("x", "y", 400))
    assert n_over > n_def
    # the replay record reconstructs the plan exactly
    meta = a.to_meta()
    r = FaultPlan.from_spec(meta["spec"], meta["seed"])
    assert r.decisions("a1", "a2", 100) == a.decisions("a1", "a2", 100)
    assert r.crashes == a.crashes == {"a9": 1.5}


@pytest.mark.chaos
def test_fault_plan_partitions_and_spec_errors():
    from pydcop_tpu.faults import FaultPlan, FaultSpecError

    p = FaultPlan.from_spec(SPEC, 0)
    # bidirectional window, active only inside [start, end)
    assert p.partition_heal("a1", "a3", 1.0) == 2.5
    assert p.partition_heal("a3", "a1", 1.0) == 2.5
    assert p.partition_heal("a1", "a3", 0.4) is None
    assert p.partition_heal("a1", "a3", 2.6) is None
    assert p.partition_heal("a1", "a2", 1.0) is None
    # agent-wide and directed forms
    q = FaultPlan.from_spec("partition=a1-*@0+1,partition=b1>b2@0+1", 0)
    assert q.partition_heal("a1", "zz", 0.5) == 1.0
    assert q.partition_heal("zz", "a1", 0.5) == 1.0
    assert q.partition_heal("b1", "b2", 0.5) == 1.0
    assert q.partition_heal("b2", "b1", 0.5) is None
    for bad in (
        "drop=1.5", "bogus=1", "delay=0.1:-2", "partition=a1@3",
        "crash=a1", "a1:drop=0.1", "drop=x",
    ):
        with pytest.raises(FaultSpecError):
            FaultPlan.from_spec(bad, 0)
    # crash-only plans carry no message faults (the `run` command's
    # eligibility check)
    assert not FaultPlan.from_spec("crash=a1@2", 0).message_faults_configured
    assert FaultPlan.from_spec("drop=0.1", 0).message_faults_configured


@pytest.mark.chaos
def test_chaos_layer_identical_event_sequence():
    """Driving the SAME message sequence through two chaos layers with
    the same plan yields the identical injected-event list AND the
    identical delivered-message sequence (no delay clauses, so no
    timing in play) — the end-to-end replay guarantee."""
    from pydcop_tpu.faults import ChaosCommunicationLayer, FaultPlan
    from pydcop_tpu.infrastructure.communication import (
        InProcessCommunicationLayer,
        Messaging,
    )
    from pydcop_tpu.infrastructure.computations import Message

    def run_once():
        inner = InProcessCommunicationLayer()
        inbox = Messaging("a2")
        inner.register("a2", inbox)
        layer = ChaosCommunicationLayer(
            inner,
            FaultPlan.from_spec("drop=0.25,dup=0.15,reorder=0.2", 9),
            "a1",
        )
        try:
            for i in range(120):
                layer.send_msg("a2", "c1", "c2", Message("m", i))
            time.sleep(0.35)  # a trailing reorder hold releases by timer
            delivered = []
            while True:
                item = inbox.next_msg(timeout=0.01)
                if item is None:
                    break
                delivered.append(item[2].content)
                inbox.task_done()
            return list(layer.events), delivered
        finally:
            layer.close()

    ev1, d1 = run_once()
    ev2, d2 = run_once()
    assert ev1 == ev2 and len(ev1) > 10
    assert d1 == d2
    kinds = {k for k, _, _ in ev1}
    assert kinds >= {"drop", "dup", "reorder"}
    # dup adds one delivery, drop removes one; reorder is count-neutral
    n_drop = sum(1 for k, _, _ in ev1 if k == "drop")
    n_dup = sum(1 for k, _, _ in ev1 if k == "dup")
    assert len(d1) == 120 - n_drop + n_dup


@pytest.mark.chaos
def test_chaos_smoke_thread_mode():
    """The tier-1 chaos smoke: a ring solved to its optimum THROUGH
    injected drops/dups/delays in thread mode, twice — same final
    cost, and the fault plan recorded in the result reproduces the
    identical decision sequence (the acceptance determinism check)."""
    from pydcop_tpu.api import solve
    from pydcop_tpu.dcop.yamldcop import load_dcop
    from pydcop_tpu.faults import FaultPlan

    dcop = load_dcop(_ring_yaml(8, agents=("a1", "a2", "a3", "a4")))
    spec = "drop=0.05,dup=0.05,delay=0.1:0.02"
    runs = [
        solve(
            dcop, "maxsum", {"damping": 0.5}, mode="thread",
            rounds=400, timeout=60, seed=1, chaos=spec, chaos_seed=7,
        )
        for _ in range(2)
    ]
    for r in runs:
        assert r["cost"] == 0.0, r["cost"]
        assert r["status"] == "finished"
        assert r["chaos"]["spec"] == spec and r["chaos"]["seed"] == 7
        assert sum(r["chaos"]["events"].values()) > 0
    assert runs[0]["cost"] == runs[1]["cost"]
    # the recorded metadata rebuilds a byte-identical plan
    plans = [
        FaultPlan.from_spec(r["chaos"]["spec"], r["chaos"]["seed"])
        for r in runs
    ]
    assert plans[0].decisions("a1", "a2", 300) == plans[1].decisions(
        "a1", "a2", 300
    )


# ---------------------------------------------------------------------------
# shared backoff helper
# ---------------------------------------------------------------------------


def test_backoff_helper_shapes_and_retry():
    from pydcop_tpu.utils.backoff import backoff_delays, call_with_backoff

    import itertools

    a = list(itertools.islice(backoff_delays(seed=3), 8))
    b = list(itertools.islice(backoff_delays(seed=3), 8))
    assert a == b  # seeded jitter is reproducible
    # exponential growth under the jitter envelope, capped
    for i, d in enumerate(a):
        base = min(0.1 * 2**i, 5.0)
        assert base <= d <= base * 1.25

    # retries until success, sleeping only simulated time
    clock = [0.0]
    sleeps = []
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 4:
            raise OSError("boom")
        return "ok"

    assert (
        call_with_backoff(
            flaky, 60.0, clock=lambda: clock[0],
            sleep=lambda s: (sleeps.append(s), clock.__setitem__(0, clock[0] + s)),
            seed=0,
        )
        == "ok"
    )
    assert len(calls) == 4 and len(sleeps) == 3

    # the deadline re-raises the LAST real failure, never overshooting
    sleeps.clear()

    def always_down():
        raise OSError("still down")

    with pytest.raises(OSError, match="still down"):
        call_with_backoff(
            always_down, 0.5, clock=lambda: clock[0],
            sleep=lambda s: (sleeps.append(s), clock.__setitem__(0, clock[0] + s)),
            seed=0,
        )
    assert sum(sleeps) <= 0.5 + 1e-9

    # giving_up aborts immediately
    calls.clear()
    with pytest.raises(OSError):
        call_with_backoff(
            flaky, 60.0, clock=lambda: clock[0], sleep=lambda s: None,
            giving_up=lambda: True,
        )
    assert len(calls) == 1


# ---------------------------------------------------------------------------
# TCP plane: bounded reconnect/resend + receiver dedupe
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_tcp_writer_rides_out_transient_outage():
    """A destination that is down when the first frames are sent but
    comes up within the retry window receives them: the writer's
    backoff retry turns the outage into a blip, and on_send_error
    never fires (before this, the first failed connect killed the
    link permanently)."""
    from pydcop_tpu.infrastructure.communication import Messaging
    from pydcop_tpu.infrastructure.computations import Message
    from pydcop_tpu.infrastructure.hostnet import TcpCommunicationLayer

    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()

    errors = []
    sender = TcpCommunicationLayer(
        on_send_error=lambda dest, e: errors.append((dest, e)),
        retry_window=10.0,
    )
    receiver = None
    try:
        sender.set_addresses({"b": ("127.0.0.1", port)})
        for i in range(3):
            sender.send_msg("b", "c1", "c2", Message("m", i))
        time.sleep(0.5)  # the outage: nothing listening yet
        receiver = TcpCommunicationLayer(port=port)
        inbox = Messaging("b")
        receiver.register("b", inbox)
        deadline = time.time() + 12
        while inbox.count_msg < 3 and time.time() < deadline:
            time.sleep(0.05)
        assert inbox.count_msg == 3, inbox.count_msg
        got = sorted(
            inbox.next_msg(timeout=1)[2].content for _ in range(3)
        )
        assert got == [0, 1, 2]
        assert not errors, errors
    finally:
        sender.close()
        if receiver is not None:
            receiver.close()


@pytest.mark.chaos
def test_tcp_receiver_dedupes_resent_frames():
    """Reconnect-resend may replay frames the peer already received;
    the receiver drops frames at or below the (sender, seq) high-water
    mark so `delivered` never double-counts — the exactly-once
    property the two-counter quiescence ledger needs."""
    import json

    from pydcop_tpu.infrastructure.communication import Messaging
    from pydcop_tpu.infrastructure.computations import Message
    from pydcop_tpu.infrastructure.hostnet import TcpCommunicationLayer
    from pydcop_tpu.utils.simple_repr import simple_repr

    receiver = TcpCommunicationLayer()
    inbox = Messaging("b")
    receiver.register("b", inbox)
    try:
        frames = []
        for sq in (1, 2):
            frames.append(
                json.dumps(
                    {
                        "da": "b", "sc": "c1", "dc": "c2", "p": 20,
                        "m": simple_repr(Message("m", sq)),
                        "sa": "1.2.3.4:999", "sq": sq,
                    }
                ).encode() + b"\n"
            )
        with socket.create_connection(receiver.address) as c1:
            c1.sendall(frames[0] + frames[1])
            time.sleep(0.3)
        # "reconnect": the whole batch replayed plus one new frame
        new = frames[1].replace(b'"sq": 2', b'"sq": 3').replace(
            b'"content": 2', b'"content": 3'
        )
        with socket.create_connection(receiver.address) as c2:
            c2.sendall(frames[0] + frames[1] + new)
            time.sleep(0.3)
        deadline = time.time() + 5
        while inbox.count_msg < 3 and time.time() < deadline:
            time.sleep(0.05)
        time.sleep(0.2)  # would-be duplicates had time to land
        assert inbox.count_msg == 3, inbox.count_msg
        got = [inbox.next_msg(timeout=1)[2].content for _ in range(3)]
        assert got == [1, 2, 3]
    finally:
        receiver.close()


# ---------------------------------------------------------------------------
# hostnet end-to-end: heal vs degrade around the grace window
# ---------------------------------------------------------------------------


def _run_chaos_orchestrator(dcop, algo, params, port, **kw):
    """run_host_orchestrator in a thread + 2 real agent processes."""
    from pydcop_tpu.infrastructure.hostnet import run_host_orchestrator

    box = {}

    def orch():
        try:
            box["result"] = run_host_orchestrator(
                dcop, algo, params, nb_agents=2, port=port,
                register_timeout=60.0, **kw,
            )
        except Exception as e:
            box["error"] = f"{type(e).__name__}: {e}"

    t = threading.Thread(target=orch, daemon=True)
    t.start()
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["PYDCOP_TPU_PLATFORM"] = "cpu"
    agents = [
        subprocess.Popen(
            [
                sys.executable, "-m", "pydcop_tpu", "agent",
                "--names", name, "--runtime", "host",
                "--orchestrator", f"localhost:{port}",
            ],
            env=env, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
        )
        for name in ("a1", "a2")
    ]
    try:
        t.join(120)
        assert not t.is_alive(), "orchestrator hung"
        return box
    finally:
        for p in agents:
            if p.poll() is None:
                p.kill()
            p.communicate()


@pytest.mark.chaos
def test_partition_shorter_than_grace_heals_identically():
    """Acceptance: an injected link partition SHORTER than the grace
    window only delays messages — the run completes with the same
    final assignment as the fault-free run (dpop: deterministic exact
    assignment, so 'same' is exact equality, not just equal cost)."""
    from pydcop_tpu.api import solve
    from pydcop_tpu.dcop.yamldcop import load_dcop

    dcop = load_dcop(_ring_yaml(8))
    base = solve(
        dcop, "dpop", mode="process", nb_agents=2, rounds=400,
        timeout=90, seed=1,
    )
    assert base["status"] == "finished"
    healed = solve(
        dcop, "dpop", mode="process", nb_agents=2, rounds=400,
        timeout=90, seed=1, chaos="partition=a1-a2@0.0+2.0",
        chaos_seed=1,
    )
    assert healed["status"] == "finished"
    assert healed["assignment"] == base["assignment"]
    assert healed["cost"] == base["cost"]
    # the partition actually bit: holds were injected and recorded
    assert healed["chaos"]["events"].get("hold", 0) > 0, healed["chaos"]


@pytest.mark.chaos
def test_partition_longer_than_grace_degrades():
    """Acceptance: a partition OUTLIVING the grace window returns the
    anytime-best assignment with status='degraded' (plus the degraded
    record and the chaos replay metadata) instead of raising."""
    from pydcop_tpu.dcop.yamldcop import load_dcop

    dcop = load_dcop(_ring_yaml(8))
    port = 9621 + (os.getpid() % 120)
    box = _run_chaos_orchestrator(
        dcop, "maxsum", {"damping": 0.5}, port,
        rounds=100_000, timeout=60, seed=2,
        chaos="partition=a1-a2@0.0+60", chaos_seed=3,
        grace_period=1.5,
    )
    assert "error" not in box, box.get("error")
    r = box["result"]
    assert r["status"] == "degraded"
    assert r["degraded"]["peer"] in ("a1", "a2")
    assert set(r["assignment"]) == {f"v{i}" for i in range(8)}
    assert r["chaos"]["seed"] == 3
    assert r["chaos"]["events"].get("partition", 0) > 0


def _free_port() -> int:
    """An ephemeral port from the OS (bind 0, read, release): unlike
    the ``BASE + pid % K`` scheme the other orchestrator tests use,
    two tests in the SAME process can never collide, and a port still
    in TIME_WAIT from an earlier test in the suite is never reused."""
    with socket.socket() as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.chaos
def test_chaos_crash_schedule_triggers_repair():
    """crash=AGENT@T is the scripted SIGKILL: under k_target the
    orchestrator must repair (migrate the crashed agent's computations
    to replica holders) and finish — fault-driven exercise of the
    resilience path with no external kill choreography."""
    from pydcop_tpu.dcop.yamldcop import load_dcop

    # the crash timer starts when the agent's chaos layer comes up (at
    # deploy), so the run must deterministically bracket it: maxsum on
    # a 24-color 128-ring is compute-bound at ~d^2 ops per message and
    # quiesces ~3.5s after deploy on this box (measured), while the
    # deploy->run-start gap is ~0.2s — crash@1.5 lands mid-run with
    # >2x margin on BOTH sides, and suite load only widens the far
    # side.  The previous sizing (DSA, 3 colors) quiesced in <0.8s
    # under load and finished with zero migrations — the in-suite
    # flake this replaces.
    dcop = load_dcop(
        _ring_yaml(128, agents=("a1", "a2", "a3"), colors=24)
    )
    port = _free_port()
    from pydcop_tpu.infrastructure.hostnet import run_host_orchestrator

    box = {}

    def orch():
        try:
            box["result"] = run_host_orchestrator(
                dcop, "maxsum", {"damping": 0.5}, nb_agents=3,
                port=port, rounds=100_000, timeout=90, seed=2,
                k_target=1, register_timeout=60.0,
                chaos="crash=a2@1.5", chaos_seed=1,
            )
        except Exception as e:
            box["error"] = f"{type(e).__name__}: {e}"

    t = threading.Thread(target=orch, daemon=True)
    t.start()
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["PYDCOP_TPU_PLATFORM"] = "cpu"
    agents = [
        subprocess.Popen(
            [
                sys.executable, "-m", "pydcop_tpu", "agent",
                "--names", name, "--runtime", "host",
                "--orchestrator", f"localhost:{port}",
            ],
            env=env, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
        )
        for name in ("a1", "a2", "a3")
    ]
    try:
        t.join(120)
        assert not t.is_alive(), "orchestrator hung after crash"
        assert "error" not in box, box.get("error")
        r = box["result"]
        assert r["status"] == "finished"
        assert r["migrations"] and r["migrations"][0]["dead"] == ["a2"]
        assert set(r["placement"]) == {"a1", "a3"}
        assert set(r["assignment"]) == {f"v{i}" for i in range(128)}
        # the crashed process really hard-exited with the chaos code
        assert agents[1].wait(timeout=30) == 23
    finally:
        for p in agents:
            if p.poll() is None:
                p.kill()
            p.communicate()


# ---------------------------------------------------------------------------
# CLI wiring
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_run_command_chaos_crash_schedule(tmp_path):
    """`run --chaos crash=...` scripts deterministic remove_agent
    events for the batched dynamic engine (and rejects message-plane
    clauses, which need a message plane)."""
    import json

    yaml_file = tmp_path / "ring.yaml"
    yaml_file.write_text(_ring_yaml(8, agents=("a1", "a2", "a3")))
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["PYDCOP_TPU_PLATFORM"] = "cpu"
    proc = subprocess.run(
        [
            sys.executable, "-m", "pydcop_tpu", "run", str(yaml_file),
            "-a", "dsa", "--chaos", "crash=a2@0.5", "--chaos_seed", "4",
            "--rounds_per_second", "40", "--final_rounds", "30",
            "--seed", "1", "-k", "1", "-d", "adhoc",
        ],
        env=env, cwd=str(tmp_path), capture_output=True, text=True,
        timeout=300,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    result = json.loads(proc.stdout[proc.stdout.index("{"):])
    assert result["chaos"] == {"spec": "crash=a2@0.5", "seed": 4}
    assert any(
        e.get("action") == "remove_agent" and e.get("agent") == "a2"
        for e in result["events"]
    ), result["events"]

    bad = subprocess.run(
        [
            sys.executable, "-m", "pydcop_tpu", "run", str(yaml_file),
            "-a", "dsa", "--chaos", "drop=0.5",
        ],
        env=env, cwd=str(tmp_path), capture_output=True, text=True,
        timeout=120,
    )
    assert bad.returncode != 0
    assert "no message plane" in bad.stderr


def test_agent_and_orchestrator_reject_device_and_wire_chaos_kinds():
    """The chaos-spec symmetry contract at the host CLIs: a clause
    neither runtime can inject must be REJECTED, never silently
    ignored (graftlint's chaos-symmetry rule pins the static side of
    this; here the runtime behavior).  A device-layer kind on the
    host agent/orchestrator would otherwise record the plan as
    applied while injecting nothing."""
    from pydcop_tpu.cli import main

    for argv, needle in [
        (
            ["agent", "--names", "a1", "--orchestrator",
             "127.0.0.1:1", "--runtime", "host",
             "--chaos", "device_oom=4"],
            "device-layer",
        ),
        (
            ["agent", "--names", "a1", "--orchestrator",
             "127.0.0.1:1", "--runtime", "host",
             "--chaos", "conn_drop=0.5"],
            "wire-level",
        ),
        (
            ["orchestrator", "-a", "dsa", "--runtime", "host",
             "--chaos", "nan_inject=0.5", "nope.yaml"],
            "device-layer",
        ),
        (
            ["orchestrator", "-a", "dsa", "--runtime", "host",
             "--chaos", "frame_corrupt=1", "nope.yaml"],
            "wire-level",
        ),
    ]:
        with pytest.raises(SystemExit) as exc:
            main(argv)
        assert needle in str(exc.value), (argv, exc.value)
