"""Per-rule fixture tests for graftlint (``tools/graftlint/``).

Each rule gets a POSITIVE fixture (a violating mini-module that must
fire) and a NEGATIVE one (a clean mini-module that must stay quiet)
under ``tests/fixtures/lint/``, exercised against fixture-local
configs — rules read only the :class:`LintConfig` they are handed, so
these tests are independent of the real repository contract (which
``tests/test_lint_guard.py`` covers).

Also here: the allow-comment escape hatch, the baseline round-trip
(``--update-baseline`` then a clean run), justification preservation,
and the ``--json`` CI schema.
"""

import json
import os
import sys

import pytest

pytestmark = pytest.mark.lint

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_TOOLS = os.path.join(_REPO, "tools")
_FIXTURES = os.path.join(_REPO, "tests", "fixtures", "lint")

if _TOOLS not in sys.path:
    sys.path.insert(0, _TOOLS)

from graftlint import LintConfig, scan  # noqa: E402
from graftlint.cli import main as lint_main  # noqa: E402


_RULE_FAMILIES = {
    "import": ("jax-import-surface", "lazy-init-eager-import"),
    "purity": ("impure-call", "set-iteration"),
    "chaos": ("chaos-symmetry", "chaos-inert-field"),
    "telemetry": (
        "metric-undocumented",
        "metric-stale-doc",
        "chaos-clause-doc",
        "span-undocumented",
    ),
    "tracekey": ("bare-jit", "unhashable-closure"),
}


def _scan_family(fixture, family, **overrides):
    """Scan one fixture with only its rule family enabled — each
    family's fixtures are minimal for THEIR rules, not the others'."""
    return scan(
        _fixture_config(fixture, **overrides),
        rules=_RULE_FAMILIES[family],
    )


def _fixture_config(name, **overrides):
    base = dict(
        root=os.path.join(_FIXTURES, name),
        scan_roots=("pkg",),
        package="pkg",
        jax_free_surface=(),
        seeded_modules=(),
        chaos_plan_module="pkg/plan.py",
        chaos_kind_categories={},
        chaos_entry_points={},
        metrics_code=(),
        metrics_docs=(),
        faults_doc="docs/faults.md",
        sanctioned_jit_modules=(),
        runner_builder_modules=(),
    )
    base.update(overrides)
    return LintConfig(**base)


def _rules_fired(findings):
    return {(f.rule, f.path) for f in findings}


# -- rule 1: import hygiene ----------------------------------------------


_IMPORT_KW = dict(
    jax_free_surface=(
        "pkg/api.py",
        "pkg/surface.py",
        "pkg/lazy/__init__.py",
        "pkg/rlazy/__init__.py",
    ),
)


def test_import_hygiene_fixture_fires():
    findings = _scan_family("import_pos", "import", **_IMPORT_KW)
    fired = _rules_fired(findings)
    assert ("jax-import-surface", "pkg/api.py") in fired  # direct
    assert ("jax-import-surface", "pkg/surface.py") in fired  # transitive
    assert ("lazy-init-eager-import", "pkg/lazy/__init__.py") in fired
    # the RELATIVE-import lazy style must be matched too (lazy and
    # eager sides resolved into the same absolute namespace)
    assert ("lazy-init-eager-import", "pkg/rlazy/__init__.py") in fired
    # the transitive finding names the chain, not just the fact
    transitive = next(
        f for f in findings if f.path == "pkg/surface.py"
    )
    assert "pkg/heavy.py" in transitive.message
    # heavy.py is OFF the surface: module-level jax is legal there
    assert not any(f.path == "pkg/heavy.py" for f in findings)


def test_import_hygiene_fixture_quiet():
    assert _scan_family("import_neg", "import", **_IMPORT_KW) == []


# -- rule 2: determinism purity ------------------------------------------


_PURITY_KW = dict(seeded_modules=("pkg/seeded.py",))


def test_purity_fixture_fires():
    findings = _scan_family("purity_pos", "purity", **_PURITY_KW)
    details = {(f.rule, f.detail) for f in findings}
    assert ("impure-call", "time.time@decide") in details
    assert ("impure-call", "random.choice@decide") in details
    assert any(
        r == "set-iteration" and d.startswith("for-loop@fan_out")
        for r, d in details
    )
    assert any(
        r == "set-iteration" and d.startswith("list()@fan_out")
        for r, d in details
    )


def test_purity_fixture_quiet_and_allow_marker():
    # the negative fixture CONTAINS a banned call (time.time_ns) —
    # under an allow[impure-call] marker, the audited-exception path
    assert _scan_family("purity_neg", "purity", **_PURITY_KW) == []


def test_purity_stale_scope_guard():
    """A configured purity scope that matches nothing is itself a
    finding — a renamed seeded function must not silently drop its
    scope (the parseable-but-inert drift class, applied to the lint
    config)."""
    findings = _scan_family(
        "purity_neg",
        "purity",
        seeded_modules=("pkg/seeded.py", "pkg/gone.py"),
        seeded_functions={"pkg/seeded.py": ("decide", "renamed_away")},
    )
    details = {f.detail for f in findings}
    assert "stale-scope:pkg/gone.py" in details
    assert "stale-scope:renamed_away" in details
    # live scopes produce no stale-scope noise
    assert "stale-scope:decide" not in details


# -- rule 3: chaos-spec symmetry -----------------------------------------


_CHAOS_KW = dict(
    chaos_kind_categories={
        "drop": "message",
        "delay": "message",
        "zap": "device",
    },
    chaos_entry_points={
        "pkg/entry.py": {
            "message": ("message_faults_configured",),
            "device": ("device_faults_configured",),
        },
    },
)


def test_chaos_symmetry_fixture_fires():
    findings = _scan_family("chaos_pos", "chaos", **_CHAOS_KW)
    details = {(f.rule, f.detail) for f in findings}
    # the `boom=` kind is parsed but unclassified in the table
    assert ("chaos-symmetry", "unclassified:boom") in details
    # the entry point never consults the device predicate
    assert ("chaos-symmetry", "category:device") in details
    # `fizzle` parses but can never flip `configured`
    assert ("chaos-inert-field", "DeviceFaults.fizzle") in details
    # the modifier field is exempt
    assert not any("zap_after" in d for _, d in details)


def test_chaos_symmetry_fixture_quiet():
    assert _scan_family("chaos_neg", "chaos", **_CHAOS_KW) == []


def test_chaos_symmetry_stale_table_row():
    cfg = _fixture_config(
        "chaos_neg",
        **{
            **_CHAOS_KW,
            "chaos_kind_categories": {
                **_CHAOS_KW["chaos_kind_categories"],
                "ghost": "wire",  # classified but no longer parsed
            },
        },
    )
    details = {
        (f.rule, f.detail)
        for f in scan(cfg, rules=_RULE_FAMILIES["chaos"])
    }
    assert ("chaos-symmetry", "stale:ghost") in details


# -- rule 4: telemetry drift ---------------------------------------------


_TELEMETRY_KW = dict(
    metrics_code=("pkg/*",),
    metrics_docs=("docs/metrics.md",),
    chaos_kind_categories={"zap": "device"},
    trace_summary_module="pkg/summary.py",
)


def test_telemetry_drift_fixture_fires():
    findings = _scan_family("telemetry_pos", "telemetry", **_TELEMETRY_KW)
    details = {(f.rule, f.detail) for f in findings}
    assert ("metric-undocumented", "foo.hits") in details
    assert ("metric-stale-doc", "foo.gone") in details
    assert ("chaos-clause-doc", "undocumented:zap") in details
    assert ("chaos-clause-doc", "stale:pow") in details
    # documented + emitted names stay quiet, incl. the f-string family
    assert not any(d == "foo.requests" for _, d in details)
    assert not any(d.startswith("bar.") for _, d in details)
    # span-undocumented: every extraction channel fires — a bare
    # compare, a *_SPAN constant, a startswith family, a dotted .get
    # key — while the documented compare stays quiet
    assert ("span-undocumented", "svc.request") in details
    assert ("span-undocumented", "cli.attempt") in details
    assert ("span-undocumented", "ring.*") in details
    assert ("span-undocumented", "svc.drain") in details
    assert not any(d == "svc.queue-wait" for _, d in details)


def test_telemetry_drift_fixture_quiet():
    assert _scan_family("telemetry_neg", "telemetry", **_TELEMETRY_KW) == []


# -- rule 5: trace-key stability -----------------------------------------


_TRACEKEY_KW = dict(
    sanctioned_jit_modules=("pkg/helper.py",),
    runner_builder_modules=("pkg/builder.py",),
)


def test_tracekey_fixture_fires():
    findings = _scan_family("tracekey_pos", "tracekey", **_TRACEKEY_KW)
    details = {(f.rule, f.detail) for f in findings}
    assert ("bare-jit", "jit@build") in details
    assert ("bare-jit", "jit@build_partial") in details  # via partial
    # the canonical plain-decorator spelling (Attribute, not Call)
    assert ("bare-jit", "jit@decorated") in details
    assert ("unhashable-closure", "build_runner:opts") in details


def test_tracekey_fixture_quiet():
    assert _scan_family("tracekey_neg", "tracekey", **_TRACEKEY_KW) == []


# -- baseline round-trip + CLI schema ------------------------------------


def test_baseline_round_trip_and_justifications(tmp_path, capsys):
    """--update-baseline pins the current findings; an immediately
    following clean run exits 0; existing justifications survive the
    rewrite and new entries are marked TODO."""
    baseline = tmp_path / "baseline.json"
    # pre-seed ONE justified entry that still exists in the repo
    baseline.write_text(
        json.dumps(
            {
                "version": 1,
                "findings": {
                    "bare-jit::tools/bench_gather.py::jit@bench": (
                        "kept: standalone microbench"
                    )
                },
            }
        )
    )
    rc = lint_main(
        ["--root", _REPO, "--baseline", str(baseline), "--update-baseline"]
    )
    assert rc == 0
    capsys.readouterr()
    data = json.loads(baseline.read_text())
    assert data["version"] == 1
    assert (
        data["findings"]["bare-jit::tools/bench_gather.py::jit@bench"]
        == "kept: standalone microbench"
    )
    # anything else pinned by the rewrite is marked for review
    others = {
        k: v
        for k, v in data["findings"].items()
        if k != "bare-jit::tools/bench_gather.py::jit@bench"
    }
    assert all(v.startswith("TODO") for v in others.values())
    # the round trip: the freshly written baseline scans clean
    rc = lint_main(["--root", _REPO, "--baseline", str(baseline)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "clean" in out


def test_cli_json_schema(tmp_path, capsys):
    """--json emits (file, line, rule, message) per finding — the CI
    annotation schema — plus ok/baselined/stale."""
    baseline = tmp_path / "empty.json"  # nothing pinned: all NEW
    rc = lint_main(
        ["--root", _REPO, "--baseline", str(baseline), "--json"]
    )
    out = capsys.readouterr().out
    data = json.loads(out)
    assert set(data) >= {
        "ok",
        "findings",
        "baselined",
        "stale",
        "rules",
        "scan_seconds",
    }
    # the repo's own baselined findings surface as NEW under an empty
    # baseline, so the schema is exercised on real records
    assert rc == 1 and data["ok"] is False
    for f in data["findings"]:
        assert set(f) == {"rule", "file", "line", "message", "key"}
        assert isinstance(f["line"], int) and f["line"] >= 1
    assert "bare-jit" in {f["rule"] for f in data["findings"]}


def test_stale_baseline_entry_fails(tmp_path, capsys):
    """A baseline entry nothing matches any more must FAIL the run —
    fixed violations leave the baseline in the same PR."""
    baseline = tmp_path / "stale.json"
    baseline.write_text(
        json.dumps(
            {
                "version": 1,
                "findings": {
                    # the real pinned entries, so the run is otherwise
                    # clean …
                    "bare-jit::tools/bench_gather.py::jit@bench": "x",
                    "bare-jit::tools/profile_maxsum.py::jit@_bench": "x",
                    "bare-jit::tools/profile_maxsum.py::jit@main": "x",
                    # … plus one pinned ghost
                    "impure-call::pkg/ghost.py::time.time@gone": "x",
                },
            }
        )
    )
    rc = lint_main(["--root", _REPO, "--baseline", str(baseline)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "stale" in out and "ghost" in out


def test_unknown_rule_is_a_usage_error(capsys):
    rc = lint_main(["--root", _REPO, "--rule", "no-such-rule"])
    assert rc == 2
    assert "unknown rule" in capsys.readouterr().err
