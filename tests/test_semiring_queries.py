"""Structured-cell semiring queries (ISSUE 13, ``ops/semiring.py``,
``docs/semirings.md`` "Structured cells"): top-K / marginal-MAP /
expectation algebra axioms, brute-force parity on small loopy graphs
under both elimination orders, merged-sweep bit-parity, the device
paths' exactness contracts, the cell-width-aware membound budget
model, and the solver service's per-query coalescing.
"""

import itertools
import random

import numpy as np
import pytest

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import (
    AgentDef,
    Domain,
    ExternalVariable,
    Variable,
)
from pydcop_tpu.dcop.relations import NAryMatrixRelation
from pydcop_tpu.ops import semiring as sr

from tests.test_semiring import _random_dcop

pytestmark = pytest.mark.semiring


# -- brute-force references ---------------------------------------------


def _enumerate(dcop):
    """All assignments with their dcop-convention costs, sorted by
    (cost, assignment) — the k-best reference."""
    vs = sorted(dcop.variables)
    doms = {v: list(dcop.variables[v].domain.values) for v in vs}
    rows = []
    for combo in itertools.product(*(doms[v] for v in vs)):
        a = dict(zip(vs, combo))
        rows.append((dcop.solution_cost(a), a))
    rows.sort(key=lambda t: (t[0], sorted(t[1].items())))
    return rows


def _brute_marginal_map(dcop, map_vars, beta=1.0):
    """max over map_vars of ``log Σ_{rest} exp(-beta·E)`` plus its
    argmax (host-f64 enumeration)."""
    vs = sorted(dcop.variables)
    doms = {v: list(dcop.variables[v].domain.values) for v in vs}
    rest = [v for v in vs if v not in map_vars]
    best = None
    for combo in itertools.product(*(doms[v] for v in map_vars)):
        fixed = dict(zip(map_vars, combo))
        logw = []
        for c2 in itertools.product(*(doms[v] for v in rest)):
            a = {**fixed, **dict(zip(rest, c2))}
            logw.append(-beta * dcop.solution_cost(a))
        logw = np.asarray(logw)
        m = logw.max()
        v = float(m + np.log(np.exp(logw - m).sum()))
        if best is None or v > best[0]:
            best = (v, fixed)
    return best


def _brute_expectation(dcop, beta=1.0):
    """(log_z, E[cost]) under the Gibbs distribution."""
    rows = _enumerate(dcop)
    logw = np.asarray([-beta * c for c, _ in rows])
    m = logw.max()
    log_z = float(m + np.log(np.exp(logw - m).sum()))
    p = np.exp(logw - log_z)
    e_cost = float(sum(pi * c for pi, (c, _) in zip(p, rows)))
    return log_z, e_cost


# -- cell algebra axioms ------------------------------------------------


@pytest.mark.parametrize("name", ["kbest:4", "expectation"])
def test_structured_semiring_axioms(name):
    """⊕/⊗ axioms on structured CELLS: associativity, commutativity,
    identities, the ⊕-identity annihilating ⊗, and distributivity —
    the reorderings the sweep relies on, now on vector cells."""
    s = sr.get_semiring(name)
    rnd = np.random.RandomState(3)

    def cell(seed):
        r = np.random.RandomState(seed)
        if s.kind == "kbest":
            return np.sort(
                r.uniform(-3, 3, size=(7, s.cell_width)), axis=-1
            )
        return np.stack(
            [r.uniform(-3, 0, size=7), r.uniform(-2, 2, size=7)],
            axis=-1,
        )

    a, b, c = cell(0), cell(1), cell(2)

    def approx(x, y):
        np.testing.assert_allclose(x, y, rtol=0, atol=1e-9)

    # ⊕: associative, commutative, identity
    approx(s.add(s.add(a, b), c), s.add(a, s.add(b, c)))
    approx(s.add(a, b), s.add(b, a))
    ident = np.broadcast_to(s.identity_cell(), a.shape)
    approx(s.add(a, ident), a)
    # ⊗: associative, commutative, identity
    approx(
        s.combine(s.combine(a, b), c), s.combine(a, s.combine(b, c))
    )
    approx(s.combine(a, b), s.combine(b, a))
    tident = np.broadcast_to(s.times_identity_cell(), a.shape)
    approx(s.combine(a, tident), a)
    # the ⊕-identity annihilates ⊗
    if s.kind == "kbest":
        assert np.all(np.isinf(s.combine(a, ident)))
    else:  # expectation: the weight plane annihilates
        assert np.all(np.isneginf(s.combine(a, ident)[..., 0]))
    # distributivity: a ⊗ (b ⊕ c) == (a ⊗ b) ⊕ (a ⊗ c)
    approx(
        s.combine(a, s.add(b, c)),
        s.add(s.combine(a, b), s.combine(a, c)),
    )
    # kbest ⊕ is NOT idempotent (a ⊕ a duplicates values) — the
    # reason it runs under the per-component certificate, not the
    # min/max one
    if s.kind == "kbest":
        assert not np.array_equal(s.add(a, a), a)


def test_kbest_reduce_matches_flat_sort():
    s = sr.kbest_semiring(3)
    rnd = np.random.RandomState(0)
    a = np.sort(rnd.uniform(0, 5, size=(4, 5, 3)), axis=-1)
    got = s.reduce(a, axis=(0, 1))
    ref = np.sort(a.reshape(-1))[:3]
    np.testing.assert_allclose(got, ref, atol=0)


# -- registry / query parsing (the nearest-name satellite) --------------


def test_get_semiring_suggests_nearest_name():
    with pytest.raises(ValueError, match="did you mean 'log_sum_exp'"):
        sr.get_semiring("log_sumexp")
    with pytest.raises(ValueError, match="unknown semiring"):
        sr.get_semiring("tropical_typo")
    # parametric kbest resolves (and caches) on demand
    assert sr.get_semiring("kbest:7").cell_width == 7
    assert sr.get_semiring("kbest:7") is sr.kbest_semiring(7)
    with pytest.raises(ValueError, match="2 <= k"):
        sr.get_semiring("kbest:1")
    with pytest.raises(ValueError, match="malformed"):
        sr.get_semiring("kbest:five")


def test_parse_query_suggests_nearest_query():
    from pydcop_tpu.api import infer_many

    for bad, expect in (
        ("kbset:5", "kbest:5"),
        ("marginal_maps", "marginal_map"),
        ("expectatin", "expectation"),
    ):
        with pytest.raises(
            ValueError, match=f"did you mean '{expect}'"
        ):
            infer_many([_random_dcop(4, 0)], bad)
    with pytest.raises(ValueError, match="unknown query"):
        infer_many([_random_dcop(4, 0)], "entropy")


def test_query_validation():
    from pydcop_tpu.api import infer

    d = _random_dcop(5, 0)
    with pytest.raises(ValueError, match="needs map_vars"):
        infer(d, "marginal_map")
    with pytest.raises(ValueError, match="marginal_map"):
        infer(d, "map", map_vars=["v0"])
    with pytest.raises(ValueError, match="expectation"):
        infer(d, "log_z", external_dists={"e": {0: 1.0}})
    with pytest.raises(ValueError, match="not\n*.*variables|not "):
        infer(d, "marginal_map", map_vars=["nope"])
    with pytest.raises(ValueError, match="cannot run memory-bounded"):
        infer(d, "marginal_map", map_vars=["v0"], max_util_bytes=64)


# -- brute-force parity -------------------------------------------------


@pytest.mark.parametrize("order", ["pseudo_tree", "min_fill"])
def test_kbest_matches_brute_force(order):
    """The kbest:5 list equals the brute-force 5 smallest costs, in
    order, with 5 DISTINCT assignments whose reported costs are their
    true dcop costs (the ISSUE 13 acceptance bar)."""
    from pydcop_tpu.api import infer

    dcop = _random_dcop(7, 1)
    rows = _enumerate(dcop)
    r = infer(dcop, "kbest:5", order=order)
    assert r["status"] == "finished"
    assert len(r["solutions"]) == 5
    np.testing.assert_allclose(
        r["costs"], [c for c, _ in rows[:5]], atol=1e-9
    )
    assert r["costs"] == sorted(r["costs"])
    seen = set()
    for s in r["solutions"]:
        assert dcop.solution_cost(s["assignment"]) == pytest.approx(
            s["cost"], abs=1e-9
        )
        seen.add(tuple(sorted(s["assignment"].items())))
    assert len(seen) == 5
    # best-of-list == the MAP optimum
    assert r["cost"] == pytest.approx(rows[0][0], abs=1e-9)


def test_kbest_exact_ties_cover_the_whole_tie_class():
    """Hard-constraint-style 0/1 tables tie massively: the returned
    costs must still be the k smallest multiset, distinct
    assignments, deterministic across repeat calls."""
    from pydcop_tpu.api import infer

    dom = Domain("c", "", [0, 1, 2])
    dcop = DCOP("ring")
    vs = [Variable(f"v{i}", dom) for i in range(5)]
    for v in vs:
        dcop.add_variable(v)
    eq = np.eye(3)
    for i in range(5):
        dcop.add_constraint(
            NAryMatrixRelation(
                [vs[i], vs[(i + 1) % 5]], eq, name=f"c{i}"
            )
        )
    dcop.add_agents([AgentDef("a0")])
    rows = _enumerate(dcop)
    r1 = infer(dcop, "kbest:6")
    r2 = infer(dcop, "kbest:6")
    assert r1["costs"] == [c for c, _ in rows[:6]]
    assert r1["solutions"] == r2["solutions"]  # deterministic
    assert (
        len(
            {
                tuple(sorted(s["assignment"].items()))
                for s in r1["solutions"]
            }
        )
        == 6
    )


def test_kbest_k_exceeding_assignment_space_truncates():
    from pydcop_tpu.api import infer

    dom = Domain("d", "", [0, 1])
    dcop = DCOP("tiny")
    a = Variable("a", dom)
    dcop.add_variable(a)
    dcop.add_constraint(
        NAryMatrixRelation([a], np.array([1.0, 3.0]), name="u")
    )
    dcop.add_agents([AgentDef("ag")])
    r = infer(dcop, "kbest:5")
    assert r["costs"] == [1.0, 3.0]  # only 2 assignments exist
    assert len(r["solutions"]) == 2


@pytest.mark.parametrize("order", ["pseudo_tree", "min_fill"])
def test_marginal_map_matches_brute_force(order):
    from pydcop_tpu.api import infer

    dcop = _random_dcop(7, 2)
    mv = sorted(dcop.variables)[:3]
    value, assignment = _brute_marginal_map(dcop, mv)
    r = infer(dcop, "marginal_map", map_vars=mv, order=order)
    assert r["status"] == "finished"
    assert r["value"] == pytest.approx(value, abs=1e-6)
    assert r["assignment"] == assignment
    assert sorted(r["map_vars"]) == mv
    # the summed block must be eliminated FIRST under both heuristics
    plan = sr.build_plan(dcop, order=order, max_vars=mv)
    positions = [plan.pos[v] for v in mv]
    assert min(positions) == len(plan.order) - len(mv)


@pytest.mark.parametrize("order", ["pseudo_tree", "min_fill"])
def test_expectation_matches_brute_force(order):
    from pydcop_tpu.api import infer

    dcop = _random_dcop(7, 3)
    for beta in (1.0, 0.25):
        log_z, e_cost = _brute_expectation(dcop, beta=beta)
        r = infer(dcop, "expectation", order=order, beta=beta)
        assert r["status"] == "finished"
        assert r["e_cost"] == pytest.approx(e_cost, abs=1e-6)
        assert r["log_z"] == pytest.approx(log_z, abs=1e-6)


def test_expectation_stochastic_externals_model_e_cost():
    """external_dists turns a pinned external into a summed variable
    with its probability as weight: E[cost] and log_z match the
    host-f64 enumeration over (internal vars × external values)."""
    from pydcop_tpu.api import infer

    dom = Domain("d", "", [0, 1, 2])
    dcop = DCOP("ext")
    a = Variable("a", dom)
    b = Variable("b", dom)
    e = ExternalVariable("e", dom, value=0)
    dcop.add_variable(a)
    dcop.add_variable(b)
    dcop.add_variable(e)
    rnd = np.random.RandomState(0)
    t_ab = rnd.uniform(0, 3, (3, 3))
    t_be = rnd.uniform(0, 3, (3, 3))
    dcop.add_constraint(NAryMatrixRelation([a, b], t_ab, name="c0"))
    dcop.add_constraint(NAryMatrixRelation([b, e], t_be, name="c1"))
    dcop.add_agents([AgentDef("ag0"), AgentDef("ag1")])
    dist = {0: 0.5, 1: 0.3, 2: 0.2}
    num = den = 0.0
    for av, bv, ev in itertools.product(range(3), repeat=3):
        cost = float(
            dcop.solution_cost({"a": av, "b": bv, "e": ev})
        )
        w = np.exp(-cost) * dist[ev]
        num += w * cost
        den += w
    for order in ("pseudo_tree", "min_fill"):
        r = infer(
            dcop, "expectation", external_dists={"e": dist},
            order=order,
        )
        assert r["e_cost"] == pytest.approx(num / den, abs=1e-6)
        assert r["log_z"] == pytest.approx(
            float(np.log(den)), abs=1e-6
        )
    # string keys (the JSON / wire / CLI form) match via str fallback
    r = infer(
        dcop, "expectation",
        external_dists={"e": {str(k): v for k, v in dist.items()}},
    )
    assert r["e_cost"] == pytest.approx(num / den, abs=1e-6)
    # validation: unknown external / out-of-domain value / bad mass
    with pytest.raises(ValueError, match="not\n*.*external|names"):
        infer(dcop, "expectation", external_dists={"x": {0: 1.0}})
    with pytest.raises(ValueError, match="outside"):
        infer(dcop, "expectation", external_dists={"e": {9: 1.0}})
    with pytest.raises(ValueError, match="positive total mass"):
        infer(dcop, "expectation", external_dists={"e": {0: 0.0}})


@pytest.mark.slow
@pytest.mark.parametrize("order", ["pseudo_tree", "min_fill"])
@pytest.mark.parametrize("seed", [4, 5])
def test_queries_brute_force_12var_loopy(order, seed):
    """The full-size acceptance matrix: ≤12-var loopy graphs, every
    query, both orders (the cheap 7-var versions run in tier-1)."""
    from pydcop_tpu.api import infer

    dcop = _random_dcop(10 + (seed % 2), seed, extra_edges=3)
    rows = _enumerate(dcop)
    r = infer(dcop, "kbest:5", order=order)
    np.testing.assert_allclose(
        r["costs"], [c for c, _ in rows[:5]], atol=1e-9
    )
    mv = sorted(dcop.variables)[:3]
    value, assignment = _brute_marginal_map(dcop, mv)
    rm = infer(dcop, "marginal_map", map_vars=mv, order=order)
    assert rm["value"] == pytest.approx(value, abs=1e-6)
    assert rm["assignment"] == assignment
    log_z, e_cost = _brute_expectation(dcop)
    re = infer(dcop, "expectation", order=order)
    assert re["e_cost"] == pytest.approx(e_cost, abs=1e-6)
    assert re["log_z"] == pytest.approx(log_z, abs=1e-6)


# -- batching -----------------------------------------------------------


def test_infer_many_structured_queries_bit_identical():
    """K>1 merged sweeps return byte-identical payloads to sequential
    infer() calls for all three new queries (the solve_many batching
    contract — ISSUE 13 acceptance)."""
    from pydcop_tpu.api import infer, infer_many

    dcops = [_random_dcop(5 + s, s) for s in range(4)]
    many = infer_many(dcops, "kbest:4", pad_policy="pow2")
    for i, d in enumerate(dcops):
        one = infer(d, "kbest:4", pad_policy="pow2")
        assert many[i]["instances_batched"] == len(dcops)
        assert many[i]["costs"] == one["costs"]
        assert many[i]["solutions"] == one["solutions"]
    mv = ["v0", "v1"]
    many = infer_many(
        dcops, "marginal_map", map_vars=mv, pad_policy="pow2"
    )
    for i, d in enumerate(dcops):
        one = infer(d, "marginal_map", map_vars=mv, pad_policy="pow2")
        assert many[i]["value"] == one["value"]
        assert many[i]["assignment"] == one["assignment"]
    many = infer_many(dcops, "expectation", pad_policy="pow2")
    for i, d in enumerate(dcops):
        one = infer(d, "expectation", pad_policy="pow2")
        assert many[i]["e_cost"] == one["e_cost"]
        assert many[i]["log_z"] == one["log_z"]


# -- device paths -------------------------------------------------------


@pytest.mark.slow
def test_device_kbest_bit_identical_and_bounds_hold():
    """device='always': the kbest list is BIT-identical to host f64
    (per-component certificate + f64 re-evaluation), marginal_map's
    assignment matches with its value inside the reported bound, and
    expectation lands inside its bound.  (The tier-1 twin of this
    runs inside tools/recompile_guard.py:run_query_guard.)"""
    from pydcop_tpu.api import infer

    dcop = _random_dcop(8, 4)
    kw = dict(device="always", pad_policy="pow2")
    host = infer(dcop, "kbest:5", device="never")
    dev = infer(dcop, "kbest:5", **kw)
    assert dev["device_nodes"] > 0
    assert dev["costs"] == host["costs"]
    assert dev["solutions"] == host["solutions"]

    mv = sorted(dcop.variables)[:3]
    h = infer(dcop, "marginal_map", map_vars=mv, device="never")
    d = infer(
        dcop, "marginal_map", map_vars=mv, tol=float("inf"), **kw
    )
    assert d["device_nodes"] > 0
    assert d["assignment"] == h["assignment"]
    assert abs(d["value"] - h["value"]) <= d["error_bound"] + 1e-9

    h = infer(dcop, "expectation", device="never")
    d = infer(dcop, "expectation", tol=float("inf"), **kw)
    assert d["device_nodes"] > 0
    assert abs(d["log_z"] - h["log_z"]) <= d["error_bound"] + 1e-9
    assert d["e_cost"] == pytest.approx(h["e_cost"], abs=1e-3)


# -- counters -----------------------------------------------------------


def test_kbest_merges_and_mixed_blocks_counters():
    from pydcop_tpu.api import infer
    from pydcop_tpu.telemetry import session

    dcop = _random_dcop(6, 0)
    with session() as tel:
        infer(dcop, "kbest:3")
    counters = tel.summary()["counters"]
    assert counters["semiring.kbest_merges"] == 6  # one per node
    # a mixed sweep whose wave 0 holds both an isolated summed var
    # and an isolated maximized var crosses blocks in one wave
    dom = Domain("d", "", [0, 1])
    d2 = DCOP("mix")
    for n in ("s0", "m0"):
        d2.add_variable(Variable(n, dom))
    d2.add_constraint(
        NAryMatrixRelation(
            [d2.variables["s0"]], np.array([0.0, 1.0]), name="u"
        )
    )
    d2.add_constraint(
        NAryMatrixRelation(
            [d2.variables["m0"]], np.array([2.0, 1.0]), name="w"
        )
    )
    d2.add_agents([AgentDef("a0")])
    with session() as tel:
        r = infer(d2, "marginal_map", map_vars=["m0"])
    counters = tel.summary()["counters"]
    assert counters.get("semiring.mixed_blocks", 0) >= 1
    assert r["assignment"] == {"m0": 1}


# -- membound (the cell-width budget-model satellite) -------------------


@pytest.mark.membound
def test_plan_cut_budget_accounts_cell_width():
    """The regression the satellite names: a kbest:8 sweep under
    max_util_bytes must budget cells × cell_width × 4 bytes — the
    same byte budget buys 8× fewer cells, so the cut is at least as
    wide, never silently 8× over budget."""
    from pydcop_tpu.ops import membound as mb

    plan = sr.build_plan(_random_dcop(10, 2, extra_edges=4))
    cp1 = mb.plan_cut(plan, 256, cell_width=1)
    cp8 = mb.plan_cut(plan, 256, cell_width=8)
    assert cp8.budget_cells == cp1.budget_cells // 8
    assert cp8.width >= cp1.width
    assert cp8.cell_width == 8
    # the meta block reports BYTES including the cell width
    assert (
        cp8.bounded_peak_cells * mb.BYTES_PER_CELL * 8
        <= 256
    )


@pytest.mark.membound
def test_membound_kbest_and_expectation_exact_across_lanes():
    """Budgeted structured-cell sweeps: the kbest list is identical
    to the unbounded one (lanes partition the space; the merged list
    is exact) and stays under the cell-width-aware budget;
    expectation matches to 1e-6."""
    from pydcop_tpu.api import infer

    dcop = _random_dcop(9, 2, extra_edges=3)
    budget = 5 * 4 * 8  # 8 cells of width 5
    ref = infer(dcop, "kbest:5", device="never")
    b = infer(
        dcop, "kbest:5", device="never", max_util_bytes=budget
    )
    assert b["membound"]["cut_width"] >= 1
    assert b["membound"]["peak_table_bytes"] <= budget
    assert b["costs"] == ref["costs"]
    assert [s["assignment"] for s in b["solutions"]] == [
        s["assignment"] for s in ref["solutions"]
    ]
    ref = infer(dcop, "expectation", device="never")
    b = infer(
        dcop, "expectation", device="never", max_util_bytes=64
    )
    assert b["membound"]["cut_width"] >= 1
    assert b["e_cost"] == pytest.approx(ref["e_cost"], abs=1e-6)
    assert b["log_z"] == pytest.approx(ref["log_z"], abs=1e-6)


# -- the solver service (mixed-query coalescing acceptance) -------------


@pytest.mark.service
def test_service_coalesces_mixed_query_traffic_in_one_tick():
    """The ISSUE 13 service acceptance: mixed kbest/map/log_z traffic
    submitted together lands in ONE tick, partitions per query (the
    query joins the dispatch partition key: 3 dispatches, all 6
    requests coalesced), and every result is bit-identical to a
    sequential api.infer call."""
    from pydcop_tpu.api import infer
    from pydcop_tpu.engine.service import SolverService

    dcops = [_random_dcop(5 + s, s) for s in range(6)]
    queries = ["kbest:5", "kbest:5", "map", "map", "log_z", "log_z"]
    with SolverService(
        pad_policy="pow2", max_batch=16, max_wait=0.3
    ) as svc:
        pendings = [
            svc.submit_infer(d, q) for d, q in zip(dcops, queries)
        ]
        results = [p.result(120) for p in pendings]
        stats = svc.stats()
    assert stats["ticks"] == 1, stats
    assert stats["dispatches"] == 3, stats
    assert stats["coalesced_requests"] == 6, stats
    for d, q, r in zip(dcops, queries, results):
        one = infer(d, q, pad_policy="pow2")
        assert r["instances_batched"] == 2
        if q.startswith("kbest"):
            assert r["costs"] == one["costs"]
            assert r["solutions"] == one["solutions"]
        elif q == "map":
            assert r["assignment"] == one["assignment"]
            assert r["cost"] == one["cost"]
        else:
            assert r["log_z"] == one["log_z"]


@pytest.mark.service
def test_service_infer_validation_and_wire_round_trip():
    """submit_infer validates at admission (nearest-name hint
    included); the wire op ships every infer field and returns the
    same payload as the in-process call."""
    from pydcop_tpu.dcop.yamldcop import dcop_yaml
    from pydcop_tpu.engine.service import (
        ServiceClient,
        ServiceServer,
        SolverService,
    )

    dcop = _random_dcop(5, 0)
    with SolverService(pad_policy="pow2", max_wait=0.05) as svc:
        with pytest.raises(ValueError, match="did you mean"):
            svc.submit_infer(dcop, "kbset:5")
        with pytest.raises(ValueError, match="elimination order"):
            svc.submit_infer(dcop, "map", order="min_width")
        with pytest.raises(ValueError, match="beta"):
            svc.submit_infer(dcop, "map", beta=0.0)
        # cross-field checks fail AT ADMISSION, not a tick later
        with pytest.raises(ValueError, match="needs map_vars"):
            svc.submit_infer(dcop, "marginal_map")
        with pytest.raises(ValueError, match="marginal_map"):
            svc.submit_infer(dcop, "map", map_vars=["v0"])
        with pytest.raises(ValueError, match="expectation"):
            svc.submit_infer(
                dcop, "log_z", external_dists={"e": {0: 1.0}}
            )
        direct = svc.infer(dcop, "kbest:3")

        with ServiceServer(svc) as server:
            with ServiceClient(server.address) as client:
                txt = dcop_yaml(dcop)
                r = client.infer(txt, "kbest:3")
                assert r["costs"] == direct["costs"]
                mv = ["v0", "v1"]
                rw = client.infer(txt, "marginal_map", map_vars=mv)
                assert rw["value"] == svc.infer(
                    dcop, "marginal_map", map_vars=mv
                )["value"]
                with pytest.raises(Exception, match="did you mean"):
                    client.infer(txt, "kbset:3")
                with pytest.raises(ValueError, match="unknown infer"):
                    client.infer(txt, "map", rounds=5)


def test_service_bnb_field_on_solve_and_infer():
    """The ``bnb`` knob rides the request schema: submit validates
    it at admission (unknown values and algos without a contraction
    phase rejected), submit_infer carries it into the dispatch
    partition key, and the wire op ships it — results bit-identical
    to bnb=off (the exactness contract)."""
    from pydcop_tpu.dcop.yamldcop import dcop_yaml
    from pydcop_tpu.engine.service import (
        ServiceClient,
        ServiceServer,
        SolverService,
    )

    dcop = _random_dcop(5, 0)
    with SolverService(pad_policy="pow2", max_wait=0.05) as svc:
        with pytest.raises(ValueError, match="bnb"):
            svc.submit_infer(dcop, "map", bnb="maybe")
        with pytest.raises(ValueError, match="bnb"):
            svc.submit(dcop, "dsa", bnb="on")
        off = svc.infer(dcop, "map", bnb="off")
        on = svc.infer(dcop, "map", bnb="on")
        assert on["cost"] == off["cost"]
        assert on["assignment"] == off["assignment"]
        s_off = svc.solve(dcop, "dpop", bnb="off")
        s_on = svc.solve(dcop, "dpop", bnb="on")
        assert s_on["cost"] == s_off["cost"]
        with ServiceServer(svc) as server:
            with ServiceClient(server.address) as client:
                txt = dcop_yaml(dcop)
                rw = client.infer(txt, "map", bnb="on")
                assert rw["cost"] == on["cost"]
                sw = client.solve(txt, algo="dpop", bnb="on")
                assert sw["cost"] == s_on["cost"]


if __name__ == "__main__":
    pytest.main([__file__, "-q"])
