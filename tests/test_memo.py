"""Subtree-fingerprint message memoization (``engine/memo.py``): the
O(delta) serving path of ISSUE 18.

:class:`ExactSession` (DPOP UTIL/VALUE) and :class:`InferSession`
(semiring contraction: ``map`` / ``log_z`` / ``marginals`` /
``kbest:<k>``) pin a problem once and answer ``set_values``
follow-ups by re-contracting ONLY the nodes whose subtree fingerprint
(base structure + effective external values over the subtree) changed
— every clean subtree's message comes from the per-session memo.

The contract these tests pin: memoized follow-ups are EQUAL to a
fresh cold solve of the mutated problem (bit-identical assignments
and costs for the exact/argmin-certified queries, f64-tight for the
mass queries), the memo counters partition the node set
(``hits + recontracted == nodes``), value-keyed fingerprints re-hit
when an external flips BACK, a zero-byte memo degrades to plain
full sweeps (never to wrong answers), and the
``engine.memo_hits`` / ``engine.memo_recontractions`` /
``engine.memo_evictions`` telemetry counters meter the same events
(docs/observability.md).
"""

import pytest

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import (
    AgentDef,
    Domain,
    ExternalVariable,
    Variable,
)
from pydcop_tpu.dcop.relations import constraint_from_str
from pydcop_tpu.engine.memo import ExactSession, InferSession
from pydcop_tpu.telemetry import session

D = Domain("d", "", [0, 1, 2])


def ext_tree_dcop(n=8):
    """A chain of n variables with ONE external 'sensor' driving the
    head — a single set_values delta dirties the head's root path and
    leaves every other subtree fingerprint unchanged."""
    dcop = DCOP("memo_tree")
    vs = [Variable(f"v{i}", D) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    sensor = ExternalVariable("sensor", D, value=0)
    dcop.add_variable(sensor)
    for i in range(n - 1):
        dcop.add_constraint(
            constraint_from_str(
                f"c{i}",
                f"1 if v{i} == v{i + 1} else abs(v{i} - v{i + 1})"
                f" * 0.25 * {i + 1}",
                vs,
            )
        )
    dcop.add_constraint(
        constraint_from_str(
            "track", "0 if v0 == sensor else 2", [vs[0], sensor]
        )
    )
    dcop.add_agents([AgentDef("a0")])
    return dcop


def solve_cold(sensor_val, algo="dpop"):
    """Fresh cold solve of the mutated problem — the parity oracle."""
    from pydcop_tpu.algorithms.dpop import solve_host

    d = ext_tree_dcop()
    d.external_variables["sensor"].value = sensor_val
    return solve_host(d, {})


def infer_cold(sensor_val, query, **kw):
    from pydcop_tpu.api import infer

    d = ext_tree_dcop()
    d.external_variables["sensor"].value = sensor_val
    return infer(d, query, device="never", **kw)


# -- ExactSession (DPOP) ----------------------------------------------


@pytest.mark.dpop
def test_exact_session_deltas_match_cold_solves():
    es = ExactSession(ext_tree_dcop())
    r0 = es.solve()
    ref0 = solve_cold(0)
    assert r0["cost"] == ref0["cost"]
    assert r0["assignment"] == ref0["assignment"]
    # cold solve: nothing to hit, everything stored
    assert r0["memo"]["hits"] == 0
    assert r0["memo"]["recontracted"] == r0["memo"]["nodes"]

    for val in (1, 2, 0):
        touched = es.set_values({"sensor": val})
        assert touched == ["track"]
        r = es.solve()
        ref = solve_cold(val)
        assert r["cost"] == ref["cost"], val
        assert r["assignment"] == ref["assignment"], val
        m = r["memo"]
        assert m["hits"] + m["recontracted"] == m["nodes"]
        # one delta dirties only the tracked head's root path
        assert m["hits"] >= 1, m
        assert m["recontracted"] < m["nodes"], m


@pytest.mark.dpop
def test_exact_session_no_delta_follow_up_hits_every_node():
    es = ExactSession(ext_tree_dcop())
    es.solve()
    r = es.solve()
    assert r["memo"]["hits"] == r["memo"]["nodes"], r["memo"]
    assert r["memo"]["recontracted"] == 0


@pytest.mark.dpop
def test_exact_session_value_keyed_fingerprints_rehit_on_flip_back():
    """A -> B -> A must re-hit A's entries: fingerprints key on the
    effective external VALUES, not on a dirty bit."""
    es = ExactSession(ext_tree_dcop())
    es.solve()
    es.set_values({"sensor": 1})
    es.solve()
    es.set_values({"sensor": 0})
    r = es.solve()
    # flip-back re-hits the clean subtrees; only entries the sensor=1
    # pass overwrote (the dirty path holds ONE entry per node, latest
    # fingerprint) re-contract
    assert r["memo"]["hits"] >= 1, r["memo"]
    assert r["cost"] == solve_cold(0)["cost"]


@pytest.mark.dpop
def test_exact_session_zero_byte_memo_degrades_to_full_sweeps():
    es = ExactSession(ext_tree_dcop(), memo_bytes=0)
    es.solve()
    es.set_values({"sensor": 2})
    r = es.solve()
    assert r["memo"]["hits"] == 0
    assert r["memo"]["recontracted"] == r["memo"]["nodes"]
    ref = solve_cold(2)
    assert r["cost"] == ref["cost"]
    assert r["assignment"] == ref["assignment"]


@pytest.mark.dpop
def test_exact_session_set_values_rejects_unknown_external():
    es = ExactSession(ext_tree_dcop())
    with pytest.raises(ValueError, match="not an external"):
        es.set_values({"nope": 1})


@pytest.mark.dpop
def test_exact_session_does_not_mutate_the_caller_dcop():
    dcop = ext_tree_dcop()
    es = ExactSession(dcop)
    es.set_values({"sensor": 2})
    es.solve()
    assert dcop.external_variables["sensor"].value == 0


@pytest.mark.dpop
def test_memo_telemetry_counters_meter_hits_and_recontractions():
    with session() as tel:
        es = ExactSession(ext_tree_dcop())
        r0 = es.solve()
        es.set_values({"sensor": 1})
        r1 = es.solve()
    counters = tel.summary()["counters"]
    assert counters.get("engine.memo_hits", 0) == r1["memo"]["hits"]
    assert counters.get("engine.memo_recontractions", 0) == (
        r0["memo"]["recontracted"] + r1["memo"]["recontracted"]
    )


# -- InferSession (semiring queries) ----------------------------------


@pytest.mark.semiring
def test_infer_session_map_parity_across_deltas():
    ses = InferSession(ext_tree_dcop(), "map", device="never")
    for val in (0, 2, 0):
        ses.set_values({"sensor": val})
        r = ses.solve()
        ref = infer_cold(val, "map")
        assert r["assignment"] == ref["assignment"], val
        assert r["cost"] == ref["cost"], val
    assert ses.last_memo["hits"] >= 1


@pytest.mark.semiring
def test_infer_session_log_z_and_marginals_parity_across_deltas():
    ses = InferSession(ext_tree_dcop(), "marginals", device="never")
    for val in (0, 1, 0):
        ses.set_values({"sensor": val})
        r = ses.solve()
        ref = infer_cold(val, "marginals")
        assert r["log_z"] == pytest.approx(
            ref["log_z"], rel=1e-12, abs=1e-12
        ), val
        for v, dist in ref["marginals"].items():
            assert r["marginals"][v] == pytest.approx(
                dist, rel=1e-9, abs=1e-12
            ), (val, v)
    m = ses.last_memo
    assert m["hits"] + m["recontracted"] == m["nodes"]
    assert m["hits"] >= 1


@pytest.mark.semiring
def test_infer_session_kbest_parity_across_deltas():
    ses = InferSession(ext_tree_dcop(), "kbest:4", device="never")
    for val in (0, 2):
        ses.set_values({"sensor": val})
        r = ses.solve()
        ref = infer_cold(val, "kbest:4")
        assert [s["assignment"] for s in r["solutions"]] == [
            s["assignment"] for s in ref["solutions"]
        ], val
        assert r["costs"] == pytest.approx(ref["costs"]), val


@pytest.mark.semiring
def test_infer_session_rejects_plan_specific_queries():
    with pytest.raises(ValueError, match="no memoized session"):
        InferSession(ext_tree_dcop(), "marginal_map")
    with pytest.raises(ValueError, match="no memoized session"):
        InferSession(ext_tree_dcop(), "expectation")


@pytest.mark.semiring
def test_tiny_memo_evicts_but_stays_correct():
    """An undersized memo thrashes (evictions > 0) yet every answer
    still matches the cold oracle — eviction is a performance event,
    never a correctness event."""
    # ~372 B/entry on this workload: 1 KiB holds two-ish of the 8
    # nodes, so every sweep evicts (a cap below ONE entry would
    # instead skip the store entirely — the oversized-table path)
    ses = InferSession(
        ext_tree_dcop(), "map", device="never", memo_bytes=1024
    )
    for val in (0, 1, 2, 0):
        ses.set_values({"sensor": val})
        r = ses.solve()
        ref = infer_cold(val, "map")
        assert r["assignment"] == ref["assignment"], val
    assert ses.memo.evictions > 0
    assert ses.last_memo["evictions"] == ses.memo.evictions


if __name__ == "__main__":
    pytest.main([__file__, "-q"])
